"""Offline RL: BC, MARWIL, and discrete CQL over logged transitions.

Mirrors the reference's offline stack (`rllib/offline/`,
`rllib/algorithms/{bc,marwil,cql}/`): algorithms that learn from a fixed
dataset of logged episodes instead of live rollouts.

- BC: behavior cloning — maximize log pi(a_logged | s).
- MARWIL: advantage-weighted BC (exponentially weighted by a monte-carlo
  advantage against a learned value baseline), beta=0 reduces to BC —
  same derivation as the reference's `marwil.py`.
- CQL (discrete): double-DQN TD loss + conservative penalty
  E[logsumexp Q(s,.) - Q(s, a_logged)] (Kumar et al. 2020), the
  reference's `cql.py` adapted to the discrete Q-learner.

Datasets are columnar dicts (obs/actions/rewards/dones [+ next_obs]) —
what `collect_episodes` below records from any policy, and what
`ray_tpu.data.Datastream.from_items` rows convert to via `from_rows`.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import CartPoleEnv
from ray_tpu.rllib.models import init_mlp, mlp_forward
from ray_tpu.rllib.learner import Learner


# ------------------------------------------------------------- data layer


def collect_episodes(env_maker: Callable[[int], Any], policy_fn,
                     num_episodes: int, seed: int = 0,
                     max_steps: int = 500) -> Dict[str, np.ndarray]:
    """Roll a behavior policy to build an offline dataset.

    policy_fn(obs[np], rng) -> action. Returns columnar transitions with
    monte-carlo returns precomputed per episode (for MARWIL).
    """
    rng = np.random.default_rng(seed)
    cols: Dict[str, List] = {k: [] for k in
                             ("obs", "actions", "rewards", "next_obs",
                              "dones", "mc_returns")}
    for ep in range(num_episodes):
        env = env_maker(seed + ep)
        obs = env.reset()
        ep_obs, ep_act, ep_rew, ep_next, ep_done = [], [], [], [], []
        for _ in range(max_steps):
            a = policy_fn(obs, rng)
            nxt, r, done, _ = env.step(a)
            ep_obs.append(obs)
            ep_act.append(a)
            ep_rew.append(r)
            ep_next.append(nxt)
            ep_done.append(float(done))
            obs = nxt
            if done:
                break
        # per-episode discount-free MC return-to-go (gamma applied by algos
        # that want it; MARWIL in the reference uses gamma inside GAE — we
        # precompute undiscounted-to-go then let the algo rescale)
        ret = np.cumsum(np.asarray(ep_rew, np.float32)[::-1])[::-1]
        cols["obs"].extend(ep_obs)
        cols["actions"].extend(ep_act)
        cols["rewards"].extend(ep_rew)
        cols["next_obs"].extend(ep_next)
        cols["dones"].extend(ep_done)
        cols["mc_returns"].extend(ret.tolist())
    return {
        "obs": np.asarray(cols["obs"], np.float32),
        "actions": np.asarray(cols["actions"], np.int32),
        "rewards": np.asarray(cols["rewards"], np.float32),
        "next_obs": np.asarray(cols["next_obs"], np.float32),
        "dones": np.asarray(cols["dones"], np.float32),
        "mc_returns": np.asarray(cols["mc_returns"], np.float32),
    }


def from_rows(rows: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
    """Columnarize a list of transition dicts (e.g. Datastream rows)."""
    keys = rows[0].keys()
    return {k: np.asarray([r[k] for r in rows]) for k in keys}


def write_experiences(dataset: Dict[str, np.ndarray], path: str, *,
                      num_shards: int = 4) -> List[str]:
    """Persist logged transitions as sharded parquet THROUGH the Data
    plane (reference rllib/offline/json_writer.py role, riding
    `Datastream.write_parquet` instead of a bespoke writer). Tensor
    columns ([N, obs_dim] observations) round-trip via the parquet
    writer's FixedSizeList encoding."""
    from ray_tpu import data as rdata

    return rdata.from_numpy(dataset,
                            parallelism=num_shards).write_parquet(path)


def read_experiences(path) -> Dict[str, np.ndarray]:
    """Load an experience dataset from parquet shards through
    `ray_tpu.data.read_parquet` (reference rllib/offline/dataset_reader.py):
    shards load in parallel as Data tasks, then concatenate columnwise."""
    import glob
    import os

    from ray_tpu import data as rdata

    if isinstance(path, str) and os.path.isdir(path):
        paths = sorted(glob.glob(os.path.join(path, "*.parquet")))
    else:
        paths = path
    ds = rdata.read_parquet(paths)
    batches = list(ds.iter_batches(batch_size=1 << 30))
    return {k: np.concatenate([b[k] for b in batches]) for k in batches[0]}


# ------------------------------------------------------------- algorithms


def discounted_returns_to_go(rewards: np.ndarray, dones: np.ndarray,
                             gamma: float) -> np.ndarray:
    """Per-episode discounted return-to-go over flat transition columns;
    episode boundaries come from the dones flags."""
    out = np.zeros_like(rewards, dtype=np.float32)
    acc = 0.0
    for t in reversed(range(len(rewards))):
        if dones[t]:
            acc = 0.0
        acc = rewards[t] + gamma * acc
        out[t] = acc
    return out


class MARWILLearner(Learner):
    """Advantage-weighted BC on the Learner stack (reference marwil.py via
    core/learner); beta=0 reduces to plain BC. The policy is a swappable
    RLModule."""

    def __init__(self, obs_dim: int, num_actions: int, lr: float,
                 beta: float, vf_coeff: float, seed: int = 0, mesh=None,
                 module=None):
        from ray_tpu.rllib.rl_module import DiscreteActorCriticModule

        self.module = module or DiscreteActorCriticModule(obs_dim, num_actions)
        self._beta = beta
        self._vf_coeff = vf_coeff
        super().__init__(lr=lr, mesh=mesh, seed=seed)

    def init_params(self, seed: int):
        return self.module.init_params(seed)

    def loss(self, params, batch, extra, rng):
        import jax
        import jax.numpy as jnp

        out = self.module.forward_train(params, batch)
        dist = self.module.action_dist(out)
        logp = dist.logp(batch["actions"])
        value = out["vf"]
        adv = batch["mc_returns"] - jax.lax.stop_gradient(value)
        # normalize advantage scale (moving-average-free variant of the
        # reference's `update_averaged_advantage_norm`)
        adv_norm = adv / (jnp.sqrt(jnp.mean(adv ** 2)) + 1e-8)
        weight = jnp.where(self._beta > 0.0,
                           jnp.exp(self._beta * jnp.clip(adv_norm, -10, 10)),
                           jnp.ones_like(adv_norm))
        bc = -(jax.lax.stop_gradient(weight) * logp).mean()
        vf = ((value - batch["mc_returns"]) ** 2).mean()
        total = bc + self._vf_coeff * vf
        return total, {"bc_loss": bc, "vf_loss": vf}


class CQLLearner(Learner):
    """Discrete conservative Q-learning on the Learner stack: double-DQN TD
    target + alpha * (logsumexp_a Q - Q(s, a_logged)); the target net rides
    `extra` like DQN's."""

    def __init__(self, obs_dim: int, num_actions: int, lr: float,
                 gamma: float, cql_alpha: float, seed: int = 0, mesh=None,
                 module=None):
        from ray_tpu.rllib.rl_module import QModule

        self.module = module or QModule(obs_dim, num_actions)
        self._gamma = gamma
        self._alpha = cql_alpha
        super().__init__(lr=lr, mesh=mesh, seed=seed)

    def init_params(self, seed: int):
        return self.module.init_params(seed)

    def make_extra(self):
        return self.params  # immutable pytrees: target aliases online

    def sync_target(self) -> None:
        self.extra = self.params

    def set_weights(self, weights):
        super().set_weights(weights)
        self.sync_target()  # a restored net must not TD against a stale target

    def loss(self, params, batch, extra, rng):
        import jax
        import jax.numpy as jnp

        out = self.module.forward_train(params, batch)
        q, next_online = out["q"], out["q_next"]
        acts = batch["actions"][:, None].astype(jnp.int32)
        q_taken = jnp.take_along_axis(q, acts, axis=-1)[:, 0]
        next_a = jnp.argmax(next_online, axis=-1)
        next_target = self.module.forward_train(extra, batch)["q_next"]
        next_q = jnp.take_along_axis(next_target, next_a[:, None], axis=-1)[:, 0]
        backup = jax.lax.stop_gradient(
            batch["rewards"] + self._gamma * (1 - batch["dones"]) * next_q)
        td = ((q_taken - backup) ** 2).mean()
        conservative = (jax.scipy.special.logsumexp(q, axis=-1)
                        - q_taken).mean()
        total = td + self._alpha * conservative
        return total, {"td_loss": td, "cql_penalty": conservative}


class CRRLearner(Learner):
    """Critic-Regularized Regression on the Learner stack: expected-SARSA
    critic + advantage-weighted BC policy in one combined loss; target Q in
    `extra` with periodic hard sync."""

    def __init__(self, obs_dim: int, num_actions: int, lr: float,
                 gamma: float, beta: float, weight_type: str,
                 seed: int = 0, mesh=None):
        self._obs_dim = obs_dim
        self._num_actions = num_actions
        self._gamma = gamma
        self._beta = beta
        self._wtype = weight_type
        super().__init__(lr=lr, mesh=mesh, seed=seed)

    def init_params(self, seed: int):
        rng = np.random.default_rng(seed)
        hidden = (64, 64)
        return {
            "pi": init_mlp(rng, (self._obs_dim, *hidden, self._num_actions),
                           final_scale=0.01),
            "q": init_mlp(rng, (self._obs_dim, *hidden, self._num_actions),
                          final_scale=np.sqrt(2.0 / hidden[-1])),
        }

    def make_extra(self):
        return self.params["q"]

    def sync_target(self) -> None:
        self.extra = self.params["q"]

    def set_weights(self, weights):
        super().set_weights(weights)
        self.sync_target()  # a restored net must not TD against a stale target

    def loss(self, params, batch, extra, rng):
        import jax
        import jax.numpy as jnp

        target_q = extra
        acts = batch["actions"][:, None].astype(jnp.int32)
        q = mlp_forward(params["q"], batch["obs"], 3)
        q_taken = jnp.take_along_axis(q, acts, axis=-1)[:, 0]
        # expected-SARSA backup under the current policy
        next_logits = mlp_forward(params["pi"], batch["next_obs"], 3)
        next_pi = jax.nn.softmax(jax.lax.stop_gradient(next_logits))
        next_q = mlp_forward(target_q, batch["next_obs"], 3)
        backup = jax.lax.stop_gradient(
            batch["rewards"] + self._gamma * (1 - batch["dones"])
            * (next_pi * next_q).sum(-1))
        td = ((q_taken - backup) ** 2).mean()

        logits = mlp_forward(params["pi"], batch["obs"], 3)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, acts, axis=-1)[:, 0]
        pi = jax.nn.softmax(jax.lax.stop_gradient(logits))
        adv = jax.lax.stop_gradient(
            q_taken - (pi * jax.lax.stop_gradient(q)).sum(-1))
        weight = (jnp.where(adv > 0, 1.0, 0.0) if self._wtype == "binary"
                  else jnp.minimum(jnp.exp(adv / self._beta), 20.0))
        bc = -(weight * logp).mean()
        total = td + bc
        return total, {"td_loss": td, "crr_bc_loss": bc,
                       "mean_weight": weight.mean()}


def _resolve_offline_input(dataset, input_path):
    """Config-side input resolution: a columnar dict passes through, a
    Datastream materializes columnwise, a path reads parquet shards
    through the Data plane (reference AlgorithmConfig.offline_data
    `input_` handling, rllib/offline/dataset_reader.py)."""
    if input_path is not None:
        return read_experiences(input_path)
    if hasattr(dataset, "iter_batches"):
        batches = list(dataset.iter_batches(batch_size=1 << 30))
        return {k: np.concatenate([b[k] for b in batches])
                for k in batches[0]}
    return dataset


class _OfflineBase(Algorithm):
    """Shared setup: dataset + minibatch iterator."""

    _cfg_key = "offline_config"

    def setup(self, config: Dict[str, Any]) -> None:
        cfg = config.get(self._cfg_key) or self._default_config()
        self.cfg = cfg
        self.dataset: Dict[str, np.ndarray] = config["dataset"] \
            if "dataset" in config else cfg.dataset
        assert self.dataset is not None, "offline algorithms need a dataset"
        # Recompute return-to-go with THIS algorithm's gamma (the dataset's
        # precomputed column is undiscounted; reference MARWIL discounts).
        gamma = getattr(cfg, "gamma", 1.0)
        if gamma < 1.0 and "rewards" in self.dataset and "dones" in self.dataset:
            self.dataset = dict(self.dataset)
            self.dataset["mc_returns"] = discounted_returns_to_go(
                self.dataset["rewards"], self.dataset["dones"], gamma)
        self._rng = np.random.default_rng(cfg.seed)
        self._build_learner()

    def _minibatches(self):
        n = len(self.dataset["obs"])
        idx = self._rng.permutation(n)
        bs = self.cfg.train_batch_size
        for start in range(0, n, bs):
            sel = idx[start:start + bs]
            yield {k: v[sel] for k, v in self.dataset.items()}

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        self.learner.set_weights(weights)


class BCConfig:
    def __init__(self):
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.lr = 1e-3
        self.train_batch_size = 256
        self.dataset: Optional[Dict[str, np.ndarray]] = None
        self.seed = 0
        # MARWIL knobs (BC is beta=0)
        self.beta = 0.0
        self.vf_coeff = 1.0
        self.gamma = 0.99

    def offline_data(self, dataset=None, *, input_path=None) -> "BCConfig":
        self.dataset = _resolve_offline_input(dataset, input_path)
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown option {k!r}")
            setattr(self, k, v)
        return self

    def build(self):
        return BC({"offline_config": self})


class MARWILConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0

    def build(self):
        return MARWIL({"offline_config": self})


class MARWIL(_OfflineBase):
    """Advantage-weighted BC: loss = -exp(beta * A_norm) * log pi(a|s) +
    vf_coeff * (V - R_mc)^2. beta=0 → plain BC."""

    @staticmethod
    def _default_config():
        return MARWILConfig()

    def _build_learner(self) -> None:
        cfg = self.cfg
        self.learner = MARWILLearner(
            cfg.obs_dim, cfg.num_actions, cfg.lr, cfg.beta, cfg.vf_coeff,
            seed=cfg.seed)

    def training_step(self) -> Dict[str, Any]:
        import jax

        aux = {}
        n = 0
        for mb in self._minibatches():
            aux = self.learner.update(mb)
            n += len(mb["obs"])
        out = {k: float(v) for k, v in jax.device_get(aux).items()}
        out["num_samples_trained"] = n
        return out

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        """Greedy policy eval for offline-trained policies."""
        fwd = self.learner.module.forward_inference(
            self.learner.get_weights(), np.asarray(obs, np.float32))
        return self.learner.module.action_dist(fwd).argmax()


class BC(MARWIL):
    """Behavior cloning = MARWIL with beta=0
    (reference rllib/algorithms/bc/bc.py)."""

    @staticmethod
    def _default_config():
        return BCConfig()


class CQLConfig:
    def __init__(self):
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.lr = 1e-3
        self.gamma = 0.99
        self.cql_alpha = 1.0
        self.target_update_freq = 8
        self.train_batch_size = 256
        self.dataset: Optional[Dict[str, np.ndarray]] = None
        self.seed = 0

    def offline_data(self, dataset=None, *, input_path=None) -> "CQLConfig":
        self.dataset = _resolve_offline_input(dataset, input_path)
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown option {k!r}")
            setattr(self, k, v)
        return self

    def build(self):
        return CQL({"offline_config": self})


class CQL(_OfflineBase):
    """Discrete conservative Q-learning: double-DQN TD target + alpha *
    (logsumexp_a Q(s,a) - Q(s, a_logged))."""

    @staticmethod
    def _default_config():
        return CQLConfig()

    def _build_learner(self) -> None:
        cfg = self.cfg
        self.learner = CQLLearner(cfg.obs_dim, cfg.num_actions, cfg.lr,
                                  cfg.gamma, cfg.cql_alpha, seed=cfg.seed)
        self._step_count = 0

    def training_step(self) -> Dict[str, Any]:
        import jax

        aux = {}
        n = 0
        for mb in self._minibatches():
            aux = self.learner.update(mb)
            self._step_count += 1
            if self._step_count % self.cfg.target_update_freq == 0:
                self.learner.sync_target()
            n += len(mb["obs"])
        out = {k: float(v) for k, v in jax.device_get(aux).items()}
        out["num_samples_trained"] = n
        return out

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        fwd = self.learner.module.forward_inference(
            self.learner.get_weights(), np.asarray(obs, np.float32))
        return self.learner.module.action_dist(fwd).argmax()


class CRRConfig:
    def __init__(self):
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.lr = 1e-3
        self.gamma = 0.99
        self.weight_type = "exp"  # "exp" | "binary" (paper's f variants)
        self.beta = 1.0           # exp weight temperature
        self.target_update_freq = 8
        self.train_batch_size = 256
        self.dataset: Optional[Dict[str, np.ndarray]] = None
        self.seed = 0

    def offline_data(self, dataset=None, *, input_path=None) -> "CRRConfig":
        self.dataset = _resolve_offline_input(dataset, input_path)
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown option {k!r}")
            setattr(self, k, v)
        return self

    def build(self):
        return CRR({"offline_config": self})


class CRR(_OfflineBase):
    """Critic-Regularized Regression (Wang et al. 2020; reference
    rllib/algorithms/crr): a Q critic trained by expected-SARSA TD under the
    learned policy, and a policy trained by advantage-weighted BC with
    weight f(A) = exp(A/beta) or 1[A>0], where
    A(s,a) = Q(s,a) - E_{a'~pi}Q(s,a')."""

    @staticmethod
    def _default_config():
        return CRRConfig()

    def _build_learner(self) -> None:
        cfg = self.cfg
        self.learner = CRRLearner(cfg.obs_dim, cfg.num_actions, cfg.lr,
                                  cfg.gamma, cfg.beta, cfg.weight_type,
                                  seed=cfg.seed)
        self._step_count = 0

    def training_step(self) -> Dict[str, Any]:
        import jax

        aux = {}
        n = 0
        for mb in self._minibatches():
            aux = self.learner.update(mb)
            self._step_count += 1
            if self._step_count % self.cfg.target_update_freq == 0:
                self.learner.sync_target()
            n += len(mb["obs"])
        out = {k: float(v) for k, v in jax.device_get(aux).items()}
        out["num_samples_trained"] = n
        return out

    def compute_actions(self, obs: np.ndarray) -> np.ndarray:
        p = self.learner.get_weights()["pi"]
        return np.asarray(mlp_forward(p, np.asarray(obs, np.float32),
                                      3)).argmax(-1)
