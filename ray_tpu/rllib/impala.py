"""IMPALA: asynchronous sampling with V-trace off-policy correction.

Mirrors the reference's IMPALA control flow (`rllib/algorithms/impala/`):
rollout workers sample continuously with whatever weights they last saw;
the learner consumes batches as they land (`ray.wait` on in-flight sample
futures) and corrects the policy lag with V-trace (Espeholt et al. 2018):

    rho_t = min(rho_bar, pi(a|s)/mu(a|s))
    v_s   = V(s) + sum_k gamma^k (prod c) rho delta_k

The learner update is one jitted JAX function; scan carries the V-trace
recursion so the whole correction compiles.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import CartPoleEnv
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.ppo import RolloutWorker


def vtrace_targets(behavior_logp, target_logp, rewards, values, last_value,
                   dones, gamma: float, rho_bar: float = 1.0,
                   c_bar: float = 1.0):
    """V-trace value targets + policy-gradient advantages over [T, N].

    Pure jnp; runs under jit via lax.scan (time-reversed recursion).
    """
    import jax
    import jax.numpy as jnp

    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), rho_bar)
    c = jnp.minimum(jnp.exp(target_logp - behavior_logp), c_bar)
    next_values = jnp.concatenate([values[1:], last_value[None]], axis=0)
    nonterminal = 1.0 - dones
    deltas = rho * (rewards + gamma * next_values * nonterminal - values)

    def body(acc, xs):
        delta_t, c_t, nt_t = xs
        acc = delta_t + gamma * c_t * nt_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        body, jnp.zeros_like(last_value),
        (deltas, c, nonterminal), reverse=True)
    vs = vs_minus_v + values
    next_vs = jnp.concatenate([vs[1:], last_value[None]], axis=0)
    pg_adv = rho * (rewards + gamma * next_vs * nonterminal - values)
    return vs, pg_adv


class _VTraceLearner(Learner):
    """Shared base for the v-trace family (APPO/IMPALA) on the Learner
    stack. Batches are stored BATCH-MAJOR [N, T, ...] so a mesh dp-shard of
    the leading axis splits ENV TRAJECTORIES, never the time axis the
    v-trace scan runs over; the loss transposes back to time-major
    internally (a free relayout under XLA)."""

    def __init__(self, obs_dim: int, num_actions: int, lr: float,
                 gamma: float, vf_coeff: float, entropy_coeff: float,
                 seed: int = 0, mesh=None, module=None):
        from ray_tpu.rllib.rl_module import DiscreteActorCriticModule

        self.module = module or DiscreteActorCriticModule(obs_dim, num_actions)
        self._gamma = gamma
        self._vf_coeff = vf_coeff
        self._entropy_coeff = entropy_coeff
        super().__init__(lr=lr, mesh=mesh, seed=seed)

    def init_params(self, seed: int):
        return self.module.init_params(seed)

    def _policy_terms(self, params, batch):
        """Time-major logp/values/entropy + v-trace targets; unmeshed
        batches arrive time-major already (no relayout round trip)."""
        import jax
        import jax.numpy as jnp

        keys = ("obs", "actions", "logp", "rewards", "dones")
        if self.mesh is None:
            tm = {k: batch[k] for k in keys}
        else:
            tm = {k: jnp.moveaxis(batch[k], 0, 1) for k in keys}
        out = self.module.forward_train(params, {"obs": tm["obs"]})
        dist = self.module.action_dist(out)
        logp = dist.logp(tm["actions"])
        values = out["vf"]
        vs, pg_adv = vtrace_targets(
            tm["logp"], jax.lax.stop_gradient(logp), tm["rewards"],
            jax.lax.stop_gradient(values), batch["last_value"],
            tm["dones"], self._gamma)
        return tm, dist, logp, values, vs, pg_adv

    def update_batch(self, batch_tn: Dict[str, np.ndarray]) -> Dict[str, float]:
        """Accepts the rollout layout [T, N, ...]; relayouts batch-major
        ONLY when meshed (the dp shard must split env trajectories)."""
        import jax
        import jax.numpy as jnp

        if self.mesh is None:
            batch = batch_tn
        else:
            batch = {k: (jnp.moveaxis(v, 0, 1) if np.ndim(v) >= 2 else v)
                     for k, v in batch_tn.items()}
        aux = self.update(batch)
        return {k: float(v) for k, v in jax.device_get(aux).items()}


class ImpalaLearner(_VTraceLearner):
    """Plain v-trace policy gradient (no surrogate clipping) with the
    paper's RMSProp, on the Learner stack (reference
    rllib/algorithms/impala via core/learner)."""

    def make_optimizer(self):
        import optax

        return optax.rmsprop(self._lr, decay=0.99, eps=0.1)

    def loss(self, params, batch, extra, rng):
        import jax

        tm, dist, logp, values, vs, pg_adv = self._policy_terms(params, batch)
        pg_loss = -(logp * jax.lax.stop_gradient(pg_adv)).mean()
        vf_loss = 0.5 * ((values - jax.lax.stop_gradient(vs)) ** 2).mean()
        entropy = dist.entropy().mean()
        total = (pg_loss + self._vf_coeff * vf_loss
                 - self._entropy_coeff * entropy)
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}


class ImpalaConfig:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: CartPoleEnv(seed)
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 4
        self.rollout_fragment_length = 64
        self.lr = 1e-3
        self.gamma = 0.99
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.max_inflight = 2          # sample futures in flight per worker
        self.broadcast_interval = 1    # learner updates between weight pushes
        self.seed = 0

    def environment(self, env_maker=None, *, obs_dim=None, num_actions=None):
        if env_maker is not None:
            self.env_maker = env_maker
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def rollouts(self, *, num_rollout_workers=None, num_envs_per_worker=None,
                 rollout_fragment_length=None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown IMPALA option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "IMPALA":
        return IMPALA({"impala_config": self})


class IMPALA(Algorithm):
    """Async actor-learner: keeps `max_inflight` sample calls outstanding
    per worker; each training_step consumes whatever has landed."""

    def setup(self, config: Dict[str, Any]) -> None:
        cfg: ImpalaConfig = config.get("impala_config") or ImpalaConfig()
        self.cfg = cfg
        self.learner = ImpalaLearner(
            cfg.obs_dim, cfg.num_actions, cfg.lr, cfg.gamma, cfg.vf_coeff,
            cfg.entropy_coeff, cfg.seed)
        self.workers = [
            RolloutWorker.options(num_cpus=1).remote(
                cfg.env_maker, cfg.num_envs_per_worker,
                cfg.seed + 1000 * (i + 1), cfg.obs_dim, cfg.num_actions)
            for i in range(cfg.num_rollout_workers)]
        w = self.learner.get_weights()
        ray_tpu.get([wk.set_weights.remote(w) for wk in self.workers])
        self._inflight: Dict[Any, int] = {}   # future -> worker index
        for i, wk in enumerate(self.workers):
            for _ in range(cfg.max_inflight):
                self._inflight[wk.sample.remote(cfg.rollout_fragment_length)] = i
        self._reward_history: List[float] = []
        self._total_steps = 0
        self._updates_since_broadcast = 0
        # always-present loss keys so callers never KeyError on a quiet step
        self._last_stats: Dict[str, float] = {
            "total_loss": float("nan"), "policy_loss": float("nan"),
            "vf_loss": float("nan"), "entropy": float("nan")}

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        cfg = self.cfg
        done, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                               timeout=30.0)
        n_steps = 0
        for ref in done:
            widx = self._inflight.pop(ref)
            wk = self.workers[widx]
            try:
                batch = ray_tpu.get(ref)
            except Exception:
                # worker died mid-sample (reference FaultAwareApply): push
                # current weights (it may have restarted) and resubmit
                wk.set_weights.remote(self.learner.get_weights())
                self._inflight[wk.sample.remote(cfg.rollout_fragment_length)] = widx
                continue
            self._reward_history.extend(batch.pop("episode_returns").tolist())
            batch.pop("values", None)  # learner recomputes values on-device
            jb = {k: jnp.asarray(v) for k, v in batch.items()}
            self._last_stats = self.learner.update_batch(jb)
            n_steps += batch["actions"].size
            self._total_steps += int(batch["actions"].size)
            self._updates_since_broadcast += 1
            if self._updates_since_broadcast >= cfg.broadcast_interval:
                # push fresh weights only to the worker we're about to relaunch
                wk.set_weights.remote(self.learner.get_weights())
                self._updates_since_broadcast = 0
            self._inflight[wk.sample.remote(cfg.rollout_fragment_length)] = widx
        stats = self._last_stats
        self._reward_history = self._reward_history[-100:]
        mean_reward = float(np.mean(self._reward_history)) \
            if self._reward_history else 0.0
        return {
            "episode_reward_mean": mean_reward,
            "num_env_steps_sampled": self._total_steps,
            **stats,
        }

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        self.learner.set_weights(weights)
        w = self.learner.get_weights()
        ray_tpu.get([wk.set_weights.remote(w) for wk in self.workers])

    def stop(self) -> None:
        self._kill_workers(self.workers)
