"""IMPALA: asynchronous sampling with V-trace off-policy correction.

Mirrors the reference's IMPALA control flow (`rllib/algorithms/impala/`):
rollout workers sample continuously with whatever weights they last saw;
the learner consumes batches as they land (`ray.wait` on in-flight sample
futures) and corrects the policy lag with V-trace (Espeholt et al. 2018):

    rho_t = min(rho_bar, pi(a|s)/mu(a|s))
    v_s   = V(s) + sum_k gamma^k (prod c) rho delta_k

The learner update is one jitted JAX function; scan carries the V-trace
recursion so the whole correction compiles.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import CartPoleEnv
from ray_tpu.rllib.ppo import RolloutWorker, init_policy_params, policy_apply


def vtrace_targets(behavior_logp, target_logp, rewards, values, last_value,
                   dones, gamma: float, rho_bar: float = 1.0,
                   c_bar: float = 1.0):
    """V-trace value targets + policy-gradient advantages over [T, N].

    Pure jnp; runs under jit via lax.scan (time-reversed recursion).
    """
    import jax
    import jax.numpy as jnp

    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), rho_bar)
    c = jnp.minimum(jnp.exp(target_logp - behavior_logp), c_bar)
    next_values = jnp.concatenate([values[1:], last_value[None]], axis=0)
    nonterminal = 1.0 - dones
    deltas = rho * (rewards + gamma * next_values * nonterminal - values)

    def body(acc, xs):
        delta_t, c_t, nt_t = xs
        acc = delta_t + gamma * c_t * nt_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        body, jnp.zeros_like(last_value),
        (deltas, c, nonterminal), reverse=True)
    vs = vs_minus_v + values
    next_vs = jnp.concatenate([vs[1:], last_value[None]], axis=0)
    pg_adv = rho * (rewards + gamma * next_vs * nonterminal - values)
    return vs, pg_adv


class ImpalaLearner:
    def __init__(self, obs_dim: int, num_actions: int, lr: float,
                 gamma: float, vf_coeff: float, entropy_coeff: float,
                 seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        self.params = init_policy_params(seed, obs_dim, num_actions)
        self.optimizer = optax.rmsprop(lr, decay=0.99, eps=0.1)
        self.opt_state = self.optimizer.init(self.params)

        def loss_fn(params, batch):
            T, N = batch["actions"].shape
            logits, values = policy_apply(params, batch["obs"])  # [T,N,A],[T,N]
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None], axis=-1)[..., 0]
            vs, pg_adv = vtrace_targets(
                batch["logp"], jax.lax.stop_gradient(logp), batch["rewards"],
                jax.lax.stop_gradient(values), batch["last_value"],
                batch["dones"], gamma)
            pg_loss = -(logp * jax.lax.stop_gradient(pg_adv)).mean()
            vf_loss = 0.5 * ((values - jax.lax.stop_gradient(vs)) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg_loss + vf_coeff * vf_loss - entropy_coeff * entropy
            return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        def update(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = loss
            return params, opt_state, aux

        self._update = jax.jit(update)

    def update_batch(self, batch) -> Dict[str, float]:
        import jax

        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, batch)
        return {k: float(v) for k, v in jax.device_get(aux).items()}

    def get_weights(self):
        import jax

        return {k: np.asarray(v) for k, v in jax.device_get(self.params).items()}

    def set_weights(self, weights):
        import jax.numpy as jnp

        self.params = {k: jnp.asarray(v) for k, v in weights.items()}
        self.opt_state = self.optimizer.init(self.params)


class ImpalaConfig:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: CartPoleEnv(seed)
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 4
        self.rollout_fragment_length = 64
        self.lr = 1e-3
        self.gamma = 0.99
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.max_inflight = 2          # sample futures in flight per worker
        self.broadcast_interval = 1    # learner updates between weight pushes
        self.seed = 0

    def environment(self, env_maker=None, *, obs_dim=None, num_actions=None):
        if env_maker is not None:
            self.env_maker = env_maker
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def rollouts(self, *, num_rollout_workers=None, num_envs_per_worker=None,
                 rollout_fragment_length=None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown IMPALA option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "IMPALA":
        return IMPALA({"impala_config": self})


class IMPALA(Algorithm):
    """Async actor-learner: keeps `max_inflight` sample calls outstanding
    per worker; each training_step consumes whatever has landed."""

    def setup(self, config: Dict[str, Any]) -> None:
        cfg: ImpalaConfig = config.get("impala_config") or ImpalaConfig()
        self.cfg = cfg
        self.learner = ImpalaLearner(
            cfg.obs_dim, cfg.num_actions, cfg.lr, cfg.gamma, cfg.vf_coeff,
            cfg.entropy_coeff, cfg.seed)
        self.workers = [
            RolloutWorker.options(num_cpus=1).remote(
                cfg.env_maker, cfg.num_envs_per_worker,
                cfg.seed + 1000 * (i + 1), cfg.obs_dim, cfg.num_actions)
            for i in range(cfg.num_rollout_workers)]
        w = self.learner.get_weights()
        ray_tpu.get([wk.set_weights.remote(w) for wk in self.workers])
        self._inflight: Dict[Any, int] = {}   # future -> worker index
        for i, wk in enumerate(self.workers):
            for _ in range(cfg.max_inflight):
                self._inflight[wk.sample.remote(cfg.rollout_fragment_length)] = i
        self._reward_history: List[float] = []
        self._total_steps = 0
        self._updates_since_broadcast = 0
        # always-present loss keys so callers never KeyError on a quiet step
        self._last_stats: Dict[str, float] = {
            "total_loss": float("nan"), "policy_loss": float("nan"),
            "vf_loss": float("nan"), "entropy": float("nan")}

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        cfg = self.cfg
        done, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                               timeout=30.0)
        n_steps = 0
        for ref in done:
            widx = self._inflight.pop(ref)
            wk = self.workers[widx]
            try:
                batch = ray_tpu.get(ref)
            except Exception:
                # worker died mid-sample (reference FaultAwareApply): push
                # current weights (it may have restarted) and resubmit
                wk.set_weights.remote(self.learner.get_weights())
                self._inflight[wk.sample.remote(cfg.rollout_fragment_length)] = widx
                continue
            self._reward_history.extend(batch.pop("episode_returns").tolist())
            batch.pop("values", None)  # learner recomputes values on-device
            jb = {k: jnp.asarray(v) for k, v in batch.items()}
            self._last_stats = self.learner.update_batch(jb)
            n_steps += batch["actions"].size
            self._total_steps += int(batch["actions"].size)
            self._updates_since_broadcast += 1
            if self._updates_since_broadcast >= cfg.broadcast_interval:
                # push fresh weights only to the worker we're about to relaunch
                wk.set_weights.remote(self.learner.get_weights())
                self._updates_since_broadcast = 0
            self._inflight[wk.sample.remote(cfg.rollout_fragment_length)] = widx
        stats = self._last_stats
        self._reward_history = self._reward_history[-100:]
        mean_reward = float(np.mean(self._reward_history)) \
            if self._reward_history else 0.0
        return {
            "episode_reward_mean": mean_reward,
            "num_env_steps_sampled": self._total_steps,
            **stats,
        }

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        self.learner.set_weights(weights)
        w = self.learner.get_weights()
        ray_tpu.get([wk.set_weights.remote(w) for wk in self.workers])

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
