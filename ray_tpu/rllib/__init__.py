from ray_tpu.rllib.env import CartPoleEnv, VectorEnv
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.impala import IMPALA, ImpalaConfig
from ray_tpu.rllib.es import ES, ESConfig
from ray_tpu.rllib.replay_buffers import ReplayBuffer, PrioritizedReplayBuffer
