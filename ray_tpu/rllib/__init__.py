from ray_tpu.rllib.env import CartPoleEnv, VectorEnv
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.ppo import PPO, PPOConfig
