from ray_tpu.rllib.env import (
    CartPoleEnv, ContinuousVectorEnv, PendulumEnv, VectorEnv)
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.learner import Learner, LearnerGroup, delayed
from ray_tpu.rllib.rl_module import (
    Categorical, Deterministic, DeterministicPolicyModule,
    DiscreteActorCriticModule, QModule, RecurrentQModule, RLModule,
    SquashedGaussian, SquashedGaussianModule)
from ray_tpu.rllib.connectors import (
    ArgmaxAction, CastObsFloat32, ClipAction, Connector, ConnectorPipeline,
    EpsilonGreedy, GaussianNoise, RandomActions, SampleAction)
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.a2c import A2C, A2CConfig
from ray_tpu.rllib.a3c import A3C, A3CConfig
from ray_tpu.rllib.pg import PG, PGConfig
from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.simple_q import SimpleQ, SimpleQConfig
from ray_tpu.rllib.random_agent import RandomAgent, RandomAgentConfig
from ray_tpu.rllib.impala import IMPALA, ImpalaConfig
from ray_tpu.rllib.es import ES, ESConfig
from ray_tpu.rllib.ars import ARS, ARSConfig
from ray_tpu.rllib.apex import (ApexDDPG, ApexDDPGConfig, ApexDQN,
                                ApexDQNConfig)
from ray_tpu.rllib.sac import SAC, SACConfig
from ray_tpu.rllib.ddpg import DDPG, DDPGConfig, TD3, TD3Config
from ray_tpu.rllib.offline import (
    BC, BCConfig, CQL, CQLConfig, CRR, CRRConfig, MARWIL, MARWILConfig,
    collect_episodes, read_experiences, write_experiences)
from ray_tpu.rllib.bandit import BanditLinTS, BanditLinUCB, LinearBanditEnv
from ray_tpu.rllib.replay_buffers import ReplayBuffer, PrioritizedReplayBuffer
from ray_tpu.rllib.multi_agent import (
    MultiAgentEnv, QMix, QMixConfig, TwoStepCooperativeEnv,
    policy_mapping_rollout)
from ray_tpu.rllib.r2d2 import MemoryCorridorEnv, R2D2, R2D2Config
from ray_tpu.rllib.alpha_zero import (
    AlphaZero, AlphaZeroConfig, MCTS, TicTacToeEnv)
from ray_tpu.rllib.ddppo import DDPPO, DDPPOConfig
from ray_tpu.rllib.dt import DT, DTConfig
from ray_tpu.rllib.maddpg import MADDPG, MADDPGConfig, SpreadEnv
from ray_tpu.rllib.slateq import (
    InterestEvolutionEnv, SlateQ, SlateQConfig)
from ray_tpu.rllib.maml import MAML, MAMLConfig, SinusoidTasks
from ray_tpu.rllib.dreamer import Dreamer, DreamerConfig, PointGoalEnv
from ray_tpu.rllib.fleet import (FleetConfig, FleetDriver, FleetLearner,
                                 FleetLearnerImpl, rollout_deployment)
