"""DDPG + TD3: deterministic-policy off-policy continuous control.

Mirrors the reference's DDPG/TD3 (`rllib/algorithms/ddpg/`,
`rllib/algorithms/td3/`): deterministic tanh actor with exploration noise,
Q critic(s) with polyak targets. TD3 adds the three tricks — twin critics,
target policy smoothing, delayed actor updates — as config flags on the
same learner, exactly how the reference derives TD3 from DDPG.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import PendulumEnv
from ray_tpu.rllib.models import init_mlp, mlp_forward
from ray_tpu.rllib.replay_buffers import ReplayBuffer
from ray_tpu.rllib.learner import Learner, delayed
from ray_tpu.rllib.sac import ContinuousWorkerBase, q_value


def init_ddpg_params(seed: int, obs_dim: int, action_dim: int,
                     twin_q: bool,
                     hidden: Tuple[int, ...] = (256, 256)) -> Dict[str, Any]:
    rng = np.random.default_rng(seed)
    params = {
        "actor": init_mlp(rng, (obs_dim, *hidden, action_dim),
                          final_scale=0.01),
        "q1": init_mlp(rng, (obs_dim + action_dim, *hidden, 1)),
    }
    if twin_q:
        params["q2"] = init_mlp(rng, (obs_dim + action_dim, *hidden, 1))
    return params


def actor_apply(actor_params, obs, max_action: float):
    import jax.numpy as jnp

    return jnp.tanh(
        mlp_forward(actor_params, obs, len(actor_params) // 2)) * max_action


@ray_tpu.remote
class NoisyActorWorker(ContinuousWorkerBase):
    """Env actor for DDPG/TD3: DeterministicPolicyModule + the
    SampleAction -> GaussianNoise connector pipeline (exploration is a
    pipeline edit, not worker code)."""

    def __init__(self, env_maker, num_envs: int, seed: int, obs_dim: int,
                 action_dim: int, max_action: float, noise_scale: float):
        self.noise_scale = noise_scale
        super().__init__(env_maker, num_envs, seed, obs_dim, action_dim,
                         max_action)

    def _make_module(self, obs_dim, action_dim, max_action):
        from ray_tpu.rllib.rl_module import DeterministicPolicyModule

        return DeterministicPolicyModule(obs_dim, action_dim, max_action)

    def _make_module_to_env(self):
        from ray_tpu.rllib.connectors import (ConnectorPipeline,
                                              GaussianNoise, SampleAction)

        return ConnectorPipeline([
            SampleAction(record_logp=False),
            GaussianNoise(self.noise_scale * self.max_action,
                          -self.max_action, self.max_action)])


class DDPGLearner(Learner):
    """Critic + (optionally delayed) actor update with polyak sync, on the
    Learner stack: ONE combined loss whose per-term stop_gradients route
    gradients (critic <- TD, actor <- Q through FROZEN critic), per-group
    optimizers via optax.multi_transform (the reference's
    configure_optimizers_for_module), the TD3 actor delay as a `delayed`
    transform with frozen inner state, and the polyak target sync as the
    jitted post_update hook."""

    def __init__(self, obs_dim: int, action_dim: int, max_action: float,
                 actor_lr: float, critic_lr: float, gamma: float, tau: float,
                 twin_q: bool, smooth_target_policy: bool,
                 target_noise: float, target_noise_clip: float,
                 seed: int = 0, policy_delay: int = 1, mesh=None):
        self._obs_dim = obs_dim
        self._action_dim = action_dim
        self._max_action = max_action
        self._actor_lr = actor_lr
        self._critic_lr = critic_lr
        self._gamma = gamma
        self._tau = tau
        self.twin_q = twin_q
        self._smooth = smooth_target_policy
        self._tnoise = target_noise
        self._tclip = target_noise_clip
        self._policy_delay = max(1, policy_delay)
        super().__init__(mesh=mesh, seed=seed)

    def init_params(self, seed: int):
        return init_ddpg_params(seed, self._obs_dim, self._action_dim,
                                self.twin_q)

    def make_optimizer(self):
        import optax

        actor_tx = optax.adam(self._actor_lr)
        if self._policy_delay > 1:
            actor_tx = delayed(actor_tx, self._policy_delay)

        def labeler(params):
            import jax

            return {k: jax.tree_util.tree_map(
                        lambda _, lbl=("actor" if k == "actor" else "critic"):
                        lbl, v)
                    for k, v in params.items()}

        return optax.multi_transform(
            {"actor": actor_tx, "critic": optax.adam(self._critic_lr)},
            labeler)

    def make_extra(self):
        import jax

        return jax.tree_util.tree_map(lambda v: np.asarray(v).copy(),
                                      self.params)

    def post_update(self, params, extra):
        import jax

        return jax.tree_util.tree_map(
            lambda t, p: (1 - self._tau) * t + self._tau * p, extra, params)

    def loss(self, params, batch, extra, rng):
        import jax
        import jax.numpy as jnp

        sg = jax.lax.stop_gradient
        next_a = actor_apply(extra["actor"], batch["next_obs"],
                             self._max_action)
        if self._smooth:
            noise = jnp.clip(
                jax.random.normal(rng, next_a.shape) * self._tnoise,
                -self._tclip, self._tclip)
            next_a = jnp.clip(next_a + noise,
                              -self._max_action, self._max_action)
        tq = q_value(extra["q1"], batch["next_obs"], next_a)
        if self.twin_q:
            tq = jnp.minimum(
                tq, q_value(extra["q2"], batch["next_obs"], next_a))
        backup = sg(batch["rewards"] + self._gamma
                    * (1 - batch["dones"]) * tq)
        # importance weights from prioritized replay (Ape-X), 1 otherwise
        w = batch.get("weights", 1.0)
        c_loss = (w * (q_value(params["q1"], batch["obs"], batch["actions"])
                       - backup) ** 2).mean()
        if self.twin_q:
            c_loss += (w * (q_value(params["q2"], batch["obs"],
                                    batch["actions"])
                            - backup) ** 2).mean()

        a = actor_apply(params["actor"], batch["obs"], self._max_action)
        a_loss = -q_value(sg(params["q1"]), batch["obs"], a).mean()

        total = c_loss + a_loss
        td = q_value(params["q1"], batch["obs"], batch["actions"]) - backup
        return total, {"critic_loss": c_loss, "actor_loss": a_loss,
                       "td": td}

    def update_batch(self, batch) -> Dict[str, float]:
        import jax

        aux = jax.device_get(self.update(batch))
        return {k: float(v) for k, v in aux.items() if np.ndim(v) == 0}

    def set_weights(self, weights):
        super().set_weights(weights)
        self.extra = self.make_extra()


class DDPGConfig:
    _algo_cls_name = "DDPG"

    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: PendulumEnv(seed)
        self.obs_dim = PendulumEnv.observation_dim
        self.action_dim = PendulumEnv.action_dim
        self.max_action = PendulumEnv.max_action
        self.num_rollout_workers = 1
        self.num_envs_per_worker = 1
        self.rollout_fragment_length = 64
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.gamma = 0.99
        self.tau = 0.005
        self.exploration_noise = 0.1
        self.buffer_size = 100_000
        self.train_batch_size = 256
        self.num_updates_per_step = 8
        self.learning_starts = 256
        # TD3 tricks (off for plain DDPG)
        self.twin_q = False
        self.smooth_target_policy = False
        self.target_noise = 0.2
        self.target_noise_clip = 0.5
        self.policy_delay = 1
        self.seed = 0

    def environment(self, env_maker=None, *, obs_dim=None, action_dim=None,
                    max_action=None):
        if env_maker is not None:
            self.env_maker = env_maker
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if action_dim is not None:
            self.action_dim = action_dim
        if max_action is not None:
            self.max_action = max_action
        return self

    def rollouts(self, *, num_rollout_workers=None, num_envs_per_worker=None,
                 rollout_fragment_length=None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown option {k!r}")
            setattr(self, k, v)
        return self

    def build(self):
        return DDPG({"ddpg_config": self})


class TD3Config(DDPGConfig):
    """DDPG config with the TD3 defaults switched on
    (reference `rllib/algorithms/td3/td3.py`)."""

    def __init__(self):
        super().__init__()
        self.twin_q = True
        self.smooth_target_policy = True
        self.policy_delay = 2

    def build(self):
        return TD3({"ddpg_config": self})


class DDPG(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        cfg: DDPGConfig = config.get("ddpg_config") or DDPGConfig()
        self.cfg = cfg
        self.learner = DDPGLearner(
            cfg.obs_dim, cfg.action_dim, cfg.max_action, cfg.actor_lr,
            cfg.critic_lr, cfg.gamma, cfg.tau, cfg.twin_q,
            cfg.smooth_target_policy, cfg.target_noise,
            cfg.target_noise_clip, cfg.seed, policy_delay=cfg.policy_delay)
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self.workers = [
            NoisyActorWorker.options(num_cpus=1).remote(
                cfg.env_maker, cfg.num_envs_per_worker,
                cfg.seed + 1000 * (i + 1), cfg.obs_dim, cfg.action_dim,
                cfg.max_action, cfg.exploration_noise)
            for i in range(cfg.num_rollout_workers)]
        self._broadcast_weights()
        self._reward_history: List[float] = []
        self._total_steps = 0

    def _broadcast_weights(self) -> None:
        from ray_tpu.rllib.learner import broadcast_weights

        broadcast_weights(self.learner.get_weights()["actor"], self.workers)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        random_phase = self._total_steps < cfg.learning_starts
        samples = ray_tpu.get([
            w.sample.remote(cfg.rollout_fragment_length, random_phase)
            for w in self.workers])
        for batch in samples:
            self.buffer.add_batch({
                k: batch[k] for k in
                ("obs", "actions", "rewards", "next_obs", "dones")})
            self._total_steps += int(batch["actions"].shape[0])
            self._reward_history.extend(batch["episode_returns"].tolist())
        self._reward_history = self._reward_history[-100:]
        stats: Dict[str, float] = {}
        if len(self.buffer) >= cfg.train_batch_size:
            for _ in range(cfg.num_updates_per_step):
                mb = self.buffer.sample(cfg.train_batch_size)
                # the actor's update period lives INSIDE the optimizer (a
                # `delayed` transform), so every call is the same jitted step
                stats = self.learner.update_batch(
                    {k: mb[k] for k in
                     ("obs", "actions", "rewards", "next_obs", "dones")})
            self._broadcast_weights()
        return {
            "episode_reward_mean": (float(np.mean(self._reward_history))
                                    if self._reward_history else 0.0),
            "num_env_steps_sampled": self._total_steps,
            **stats,
        }

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        self.learner.set_weights(weights)
        self._broadcast_weights()

    def stop(self) -> None:
        self._kill_workers(self.workers)


class TD3(DDPG):
    """TD3 = DDPG + twin critics + target smoothing + delayed actor
    (reference rllib/algorithms/td3)."""
