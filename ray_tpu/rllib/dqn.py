"""DQN: epsilon-greedy sampling fleet + replay buffer + double-DQN learner.

Mirrors the reference's DQN anatomy (`rllib/algorithms/dqn/dqn.py`:
sample → store → replay-sample → TD update → target sync) with the learner
as a single jitted JAX update (double-DQN targets, optional prioritized
replay with importance weights).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import CartPoleEnv, VectorEnv
from ray_tpu.rllib.evaluation import EvalConfigMixin
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.replay_buffers import PrioritizedReplayBuffer, ReplayBuffer


from ray_tpu.rllib.models import init_mlp, mlp_forward


def init_q_params(rng_seed: int, obs_dim: int, num_actions: int,
                  hidden: Tuple[int, ...] = (64, 64)) -> Dict[str, Any]:
    return init_mlp(np.random.default_rng(rng_seed),
                    (obs_dim, *hidden, num_actions),
                    final_scale=np.sqrt(2.0 / hidden[-1]))


def q_apply(params, obs, n_layers: int = 3):
    return mlp_forward(params, obs, n_layers)


@ray_tpu.remote
class EpsilonGreedyWorker:
    """Env-stepping actor collecting transitions under epsilon-greedy.

    Acting is MODULE + CONNECTORS (reference EnvRunner + connector
    pipelines): the worker owns a `QModule` and the `EpsilonGreedy`
    module-to-env connector — no hand-rolled action selection. The
    algorithm's per-iteration epsilon schedule is forwarded per sample
    call as an override on the connector."""

    def __init__(self, env_maker, num_envs: int, seed: int, obs_dim: int,
                 num_actions: int, module=None, env_to_module=None,
                 module_to_env=None):
        from ray_tpu.rllib.connectors import (CastObsFloat32,
                                              ConnectorPipeline,
                                              EpsilonGreedy)
        from ray_tpu.rllib.rl_module import QModule

        self.vec = VectorEnv(env_maker, num_envs, seed)
        self.obs = self.vec.reset()
        self.rng = np.random.default_rng(seed)
        self.params = None
        self.num_actions = num_actions
        self.module = module or QModule(obs_dim, num_actions)
        self.env_to_module = env_to_module or ConnectorPipeline(
            [CastObsFloat32()])
        self.module_to_env = module_to_env or ConnectorPipeline(
            [EpsilonGreedy(num_actions)])
        self._ep_returns = np.zeros(num_envs, np.float32)
        self._completed: List[float] = []

    def set_weights(self, params) -> bool:
        self.params = {k: np.asarray(v) for k, v in params.items()}
        return True

    def eval_episodes(self, num_episodes: int, seed: int = 0):
        from ray_tpu.rllib.evaluation import run_eval_episodes

        return run_eval_episodes(self.vec.env_maker, self.module,
                                 self.params, num_episodes, seed)

    def sample(self, num_steps: int, epsilon: float) -> Dict[str, np.ndarray]:
        cols = {k: [] for k in ("obs", "actions", "rewards", "next_obs", "dones")}
        for _ in range(num_steps):
            data = {"obs": self.obs, "rng": self.rng, "module": self.module,
                    "params": self.params, "epsilon_override": epsilon}
            data = self.env_to_module(data)
            data["fwd_out"] = self.module.forward_inference(self.params,
                                                            data["obs"])
            data = self.module_to_env(data)
            actions = data["actions"]
            prev_obs = self.obs
            self.obs, rewards, dones, _ = self.vec.step(actions)
            cols["obs"].append(prev_obs)
            cols["actions"].append(actions)
            cols["rewards"].append(rewards)
            cols["next_obs"].append(self.obs)
            cols["dones"].append(dones.astype(np.float32))
            self._ep_returns += rewards
            for i, d in enumerate(dones):
                if d:
                    self._completed.append(float(self._ep_returns[i]))
                    self._ep_returns[i] = 0.0
        out = {k: np.concatenate(v) if v[0].ndim > 1 else np.stack(v).reshape(-1)
               for k, v in cols.items()}
        ep, self._completed = self._completed, []
        out["episode_returns"] = np.array(ep, np.float32)
        return out


class DQNLearner(Learner):
    """Double-DQN TD update on the Learner stack; the target network rides
    through jit as the Learner's `extra` pytree. Pass `mesh=` to shard
    batches over dp (LearnerGroup mesh backend)."""

    def __init__(self, obs_dim: int, num_actions: int, lr: float,
                 gamma: float, seed: int = 0, mesh=None,
                 double_q: bool = True):
        self._obs_dim = obs_dim
        self._num_actions = num_actions
        self._gamma = gamma
        self._double_q = double_q
        super().__init__(lr=lr, mesh=mesh, seed=seed)

    def init_params(self, seed: int):
        return init_q_params(seed, self._obs_dim, self._num_actions)

    def make_extra(self):
        # params pytrees are immutable (updates build new ones), so the
        # target net can alias the online params at sync points
        return self.params

    def loss(self, params, batch, extra, rng):
        import jax
        import jax.numpy as jnp

        target_params = extra
        q = q_apply(params, batch["obs"])
        q_taken = jnp.take_along_axis(
            q, batch["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]
        next_target = q_apply(target_params, batch["next_obs"])
        if self._double_q:
            # double DQN: online net picks argmax, target net evaluates
            next_online = q_apply(params, batch["next_obs"])
            next_a = jnp.argmax(next_online, axis=-1)
            next_q = jnp.take_along_axis(
                next_target, next_a[:, None], axis=-1)[:, 0]
        else:
            # SimpleQ: plain max over the target net
            next_q = next_target.max(-1)
        target = batch["rewards"] + self._gamma * (1.0 - batch["dones"]) * \
            jax.lax.stop_gradient(next_q)
        td = q_taken - target
        w = batch.get("weights", jnp.ones_like(td))
        loss = (w * td ** 2).mean()
        return loss, {"td": td}

    def update_batch(self, batch: Dict[str, np.ndarray]):
        import jax

        aux = self.update(batch)
        aux = jax.device_get(aux)
        return float(aux["total_loss"]), np.asarray(aux["td"])

    def sync_target(self) -> None:
        self.extra = self.params

    def set_weights(self, weights):
        super().set_weights(weights)
        self.extra = self.params

    # kept for callers that referenced the old attribute name
    @property
    def target_params(self):
        return self.extra


class DQNConfig(EvalConfigMixin):
    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: CartPoleEnv(seed)
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 2
        self.rollout_fragment_length = 32
        self.lr = 5e-4
        self.gamma = 0.99
        self.double_q = True
        self.buffer_capacity = 50_000
        self.prioritized_replay = False
        self.train_batch_size = 64
        self.num_updates_per_step = 8
        self.target_update_interval = 4     # in training_steps
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 50
        self.learning_starts = 200           # min transitions before updates
        self.seed = 0

    def environment(self, env_maker=None, *, obs_dim=None, num_actions=None):
        if env_maker is not None:
            self.env_maker = env_maker
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def rollouts(self, *, num_rollout_workers=None, num_envs_per_worker=None,
                 rollout_fragment_length=None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown DQN option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "DQN":
        return DQN({"dqn_config": self})


class DQN(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        cfg: DQNConfig = config.get("dqn_config") or DQNConfig()
        self.cfg = cfg
        self.learner = DQNLearner(cfg.obs_dim, cfg.num_actions, cfg.lr,
                                  cfg.gamma, cfg.seed,
                                  double_q=getattr(cfg, "double_q", True))
        if cfg.prioritized_replay:
            self.buffer = PrioritizedReplayBuffer(cfg.buffer_capacity,
                                                  seed=cfg.seed)
        else:
            self.buffer = ReplayBuffer(cfg.buffer_capacity, seed=cfg.seed)
        self.workers = [
            EpsilonGreedyWorker.options(num_cpus=1).remote(
                cfg.env_maker, cfg.num_envs_per_worker,
                cfg.seed + 1000 * (i + 1), cfg.obs_dim, cfg.num_actions)
            for i in range(cfg.num_rollout_workers)]
        self._broadcast()
        self._reward_history: List[float] = []
        self._total_steps = 0

    def _broadcast(self) -> None:
        from ray_tpu.rllib.learner import broadcast_weights

        broadcast_weights(self.learner.get_weights(), self.workers)

    def _epsilon(self) -> float:
        cfg = self.cfg
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        eps = self._epsilon()
        samples = ray_tpu.get([
            wk.sample.remote(cfg.rollout_fragment_length, eps)
            for wk in self.workers])
        n_new = 0
        for s in samples:
            ep = s.pop("episode_returns")
            self._reward_history.extend(ep.tolist())
            self.buffer.add_batch(s)
            n_new += len(s["actions"])
            self._total_steps += len(s["actions"])
        self._reward_history = self._reward_history[-100:]

        losses = []
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_step):
                batch = self.buffer.sample(cfg.train_batch_size)
                idx = batch.pop("batch_indexes", None)
                loss, td = self.learner.update_batch(batch)
                losses.append(loss)
                if idx is not None:
                    self.buffer.update_priorities(idx, td)
            if self.iteration % cfg.target_update_interval == 0:
                self.learner.sync_target()
            self._broadcast()
        mean_reward = float(np.mean(self._reward_history)) \
            if self._reward_history else 0.0
        return {
            "episode_reward_mean": mean_reward,
            "epsilon": eps,
            "buffer_size": len(self.buffer),
            "num_env_steps_sampled": self._total_steps,
            "loss": float(np.mean(losses)) if losses else float("nan"),
        }

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        self.learner.set_weights(weights)
        self._broadcast()

    def stop(self) -> None:
        self._kill_workers(self.workers)
