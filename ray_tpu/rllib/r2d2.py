"""R2D2: recurrent replay distributed DQN (Kapturowski et al. 2019).

Mirrors the reference's R2D2 (`rllib/algorithms/r2d2/`): an LSTM-style
recurrent Q network trained on stored *sequences* with burn-in — the first
`burn_in` steps of each sampled sequence only rebuild the recurrent state
(no gradient), the remainder takes double-DQN TD updates.

The network is a `RecurrentQModule` (GRU with explicit state in/out) and
BOTH paths ride it: acting steps `forward_inference(params, obs, state)`
through the EpsilonGreedy connector pipeline, training unrolls the same
cell under jit inside an `R2D2Learner` on the Learner stack — the
recurrent proof that the module/connector contract is not MLP-only.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.learner import Learner


class MemoryCorridorEnv:
    """Cue at t=0 (one of two), corridor of `length` blank steps, then a
    binary choice; reward +1 for matching the cue, -1 otherwise."""

    def __init__(self, seed: int = 0, length: int = 4):
        self.length = length
        self.observation_dim = 3  # [cue_a, cue_b, blank]
        self.num_actions = 2
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._cue = 0

    def reset(self) -> np.ndarray:
        self._t = 0
        self._cue = int(self._rng.integers(2))
        obs = np.zeros(3, np.float32)
        obs[self._cue] = 1.0
        return obs

    def step(self, action: int):
        self._t += 1
        obs = np.zeros(3, np.float32)
        obs[2] = 1.0
        if self._t <= self.length:
            return obs, 0.0, False, {}
        r = 1.0 if action == self._cue else -1.0
        return obs, r, True, {}


class R2D2Learner(Learner):
    """Burn-in double-DQN sequence loss over `RecurrentQModule.unroll`
    (reference r2d2_torch_policy.py `r2d2_loss`): the first `burn_in`
    steps rebuild hidden state without gradient, then one EXTENDED unroll
    [obs[bi:], final next_obs] yields both taken-action and next-state Q
    values with non-stale hidden state. The target net rides as the
    Learner's `extra` pytree, synced by aliasing (params pytrees are
    immutable)."""

    def __init__(self, module, lr: float, gamma: float, burn_in: int,
                 seed: int = 0, mesh=None):
        self.module = module
        self._gamma = gamma
        self._burn_in = burn_in
        super().__init__(lr=lr, mesh=mesh, seed=seed)

    def init_params(self, seed: int):
        return self.module.init_params(seed)

    def make_extra(self):
        return self.params

    def loss(self, params, batch, extra, rng):
        import jax
        import jax.numpy as jnp

        m, bi, tp = self.module, self._burn_in, extra
        B = batch["obs"].shape[0]
        h0 = jnp.zeros((B, m.hidden))
        # burn-in: rebuild recurrent state without gradients
        _, h_start = m.unroll(jax.lax.stop_gradient(params),
                              batch["obs"][:, :bi], h0)
        h_start = jax.lax.stop_gradient(h_start)
        _, ht_start = m.unroll(tp, batch["obs"][:, :bi], h0)
        # one extended pass: [obs[bi:], final next_obs]. Since
        # next_obs[t] == obs[t+1], q_ext[:, 1:] are the next-state values
        # evaluated with the CORRECT (non-stale) hidden state.
        ext = jnp.concatenate(
            [batch["obs"][:, bi:], batch["next_obs"][:, -1:]], axis=1)
        q_ext, _ = m.unroll(params, ext, h_start)       # [B, T'+1, A]
        q_taken = jnp.take_along_axis(
            q_ext[:, :-1],
            batch["actions"][:, bi:, None].astype(jnp.int32), axis=-1)[..., 0]
        # double DQN: online picks the argmax, target evaluates
        a_star = jnp.argmax(q_ext[:, 1:], axis=-1)
        q_ext_t, _ = m.unroll(tp, ext, ht_start)
        next_q = jnp.take_along_axis(
            q_ext_t[:, 1:], a_star[..., None], axis=-1)[..., 0]
        target = batch["rewards"][:, bi:] + self._gamma * \
            (1 - batch["dones"][:, bi:]) * jax.lax.stop_gradient(next_q)
        mask = batch["mask"][:, bi:]
        td = (q_taken - target) * mask
        loss = (td ** 2).sum() / jnp.maximum(mask.sum(), 1.0)
        return loss, {}

    def sync_target(self) -> None:
        self.extra = self.params

    def set_weights(self, weights):
        super().set_weights(weights)
        self.extra = self.params


class R2D2Config:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = MemoryCorridorEnv
        self.obs_dim = 3
        self.num_actions = 2
        self.hidden = 32
        self.lr = 2e-3
        self.gamma = 0.997
        self.seq_len = 8            # stored sequence length
        self.burn_in = 2            # steps that only rebuild hidden state
        self.buffer_capacity = 2000  # sequences
        self.train_batch_size = 32
        self.episodes_per_iter = 16
        self.updates_per_iter = 4
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_iters = 40
        self.target_update_interval = 5
        self.max_episode_steps = 16
        self.seed = 0

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown R2D2 option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "R2D2":
        if not 0 <= self.burn_in < self.seq_len:
            raise ValueError(
                f"burn_in ({self.burn_in}) must be in [0, seq_len"
                f"={self.seq_len})")
        return R2D2({"r2d2_config": self})


class R2D2(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        from ray_tpu.rllib.connectors import (CastObsFloat32,
                                              ConnectorPipeline,
                                              EpsilonGreedy)
        from ray_tpu.rllib.rl_module import RecurrentQModule

        cfg: R2D2Config = config.get("r2d2_config") or R2D2Config()
        self.cfg = cfg
        self.env = cfg.env_maker(cfg.seed)
        self._np_rng = np.random.default_rng(cfg.seed)
        self.module = RecurrentQModule(cfg.obs_dim, cfg.num_actions,
                                       cfg.hidden)
        self.learner = R2D2Learner(self.module, cfg.lr, cfg.gamma,
                                   cfg.burn_in, cfg.seed)
        self.env_to_module = ConnectorPipeline([CastObsFloat32()])
        self.module_to_env = ConnectorPipeline(
            [EpsilonGreedy(cfg.num_actions)])
        # host-side numpy copy of the params for env-stepping
        self._acting_params = self.learner.get_weights()
        # sequence-major replay: each row is one [seq_len] slice
        self._sequences: List[dict] = []
        self._reward_hist: List[float] = []

    # ----------------------------------------------------------- rollouts
    def _epsilon(self) -> float:
        cfg = self.cfg
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def _collect_episode(self, epsilon: float, store: bool = True) -> float:
        cfg = self.cfg
        env = self.env
        obs = env.reset()
        state = self.module.get_initial_state(1)
        rows = {k: [] for k in ("obs", "actions", "rewards", "next_obs",
                                "dones")}
        total = 0.0
        for _ in range(cfg.max_episode_steps):
            data = {"obs": np.asarray(obs, np.float32)[None],
                    "rng": self._np_rng, "module": self.module,
                    "params": self._acting_params,
                    "epsilon_override": epsilon}
            data = self.env_to_module(data)
            fwd = self.module.forward_inference(
                self._acting_params, data["obs"], state=state)
            data["fwd_out"] = fwd
            data = self.module_to_env(data)
            a = int(data["actions"][0])
            state = np.asarray(fwd["state_out"])
            nxt, r, done, _ = env.step(a)
            rows["obs"].append(obs)
            rows["actions"].append(a)
            rows["rewards"].append(r)
            rows["next_obs"].append(nxt)
            rows["dones"].append(float(done))
            total += r
            obs = nxt
            if done:
                break
        if store:
            self._store_episode(rows)
        return total

    def _store_episode(self, rows: Dict[str, list]) -> None:
        """Chop the episode into fixed seq_len windows (zero-padded, with a
        validity mask) — R2D2's stored-sequence format."""
        cfg = self.cfg
        T = len(rows["actions"])
        for start in range(0, T, cfg.seq_len - cfg.burn_in or 1):
            end = min(start + cfg.seq_len, T)
            n = end - start
            seq = {
                "obs": np.zeros((cfg.seq_len, cfg.obs_dim), np.float32),
                "next_obs": np.zeros((cfg.seq_len, cfg.obs_dim), np.float32),
                "actions": np.zeros(cfg.seq_len, np.int32),
                "rewards": np.zeros(cfg.seq_len, np.float32),
                "dones": np.ones(cfg.seq_len, np.float32),
                "mask": np.zeros(cfg.seq_len, np.float32),
            }
            seq["obs"][:n] = rows["obs"][start:end]
            seq["next_obs"][:n] = rows["next_obs"][start:end]
            seq["actions"][:n] = rows["actions"][start:end]
            seq["rewards"][:n] = rows["rewards"][start:end]
            seq["dones"][:n] = rows["dones"][start:end]
            seq["mask"][:n] = 1.0
            self._sequences.append(seq)
            if start == 0 and end == T:
                break
        if len(self._sequences) > cfg.buffer_capacity:
            self._sequences = self._sequences[-cfg.buffer_capacity:]

    # --------------------------------------------------------------- train
    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        eps = self._epsilon()
        returns = [self._collect_episode(eps)
                   for _ in range(cfg.episodes_per_iter)]
        self._reward_hist.extend(returns)
        self._reward_hist = self._reward_hist[-200:]

        losses = []
        if len(self._sequences) >= cfg.train_batch_size:
            for _ in range(cfg.updates_per_iter):
                idx = self._np_rng.integers(0, len(self._sequences),
                                            cfg.train_batch_size)
                rows = [self._sequences[i] for i in idx]
                batch = {k: np.stack([r[k] for r in rows])
                         for k in rows[0]}
                aux = self.learner.update(batch)
                losses.append(float(aux["total_loss"]))
            if self.iteration % cfg.target_update_interval == 0:
                self.learner.sync_target()
            self._acting_params = self.learner.get_weights()
        return {
            "episode_reward_mean": float(np.mean(self._reward_hist)),
            "epsilon": eps,
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "num_sequences": len(self._sequences),
        }

    def greedy_return(self, episodes: int = 20) -> float:
        return float(np.mean([self._collect_episode(0.0, store=False)
                              for _ in range(episodes)]))

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        self.learner.set_weights(weights)
        self._acting_params = self.learner.get_weights()
