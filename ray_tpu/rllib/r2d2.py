"""R2D2: recurrent replay distributed DQN (Kapturowski et al. 2019).

Mirrors the reference's R2D2 (`rllib/algorithms/r2d2/`): an LSTM-style
recurrent Q network trained on stored *sequences* with burn-in — the first
`burn_in` steps of each sampled sequence only rebuild the recurrent state
(no gradient), the remainder takes double-DQN TD updates. The recurrent
cell is a GRU (one gate fewer than LSTM, same episodic-memory capability,
friendlier to the MXU: all gates are two fused matmuls).

The env for learning tests is a memory task (`MemoryCorridorEnv`): the
first observation carries a cue that disappears immediately and must be
recalled at the corridor's end — feedforward DQN cannot beat chance on it,
a recurrent learner can.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm


class MemoryCorridorEnv:
    """Cue at t=0 (one of two), corridor of `length` blank steps, then a
    binary choice; reward +1 for matching the cue, -1 otherwise."""

    def __init__(self, seed: int = 0, length: int = 4):
        self.length = length
        self.observation_dim = 3  # [cue_a, cue_b, blank]
        self.num_actions = 2
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._cue = 0

    def reset(self) -> np.ndarray:
        self._t = 0
        self._cue = int(self._rng.integers(2))
        obs = np.zeros(3, np.float32)
        obs[self._cue] = 1.0
        return obs

    def step(self, action: int):
        self._t += 1
        obs = np.zeros(3, np.float32)
        obs[2] = 1.0
        if self._t <= self.length:
            return obs, 0.0, False, {}
        r = 1.0 if action == self._cue else -1.0
        return obs, r, True, {}


class R2D2Config:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = MemoryCorridorEnv
        self.obs_dim = 3
        self.num_actions = 2
        self.hidden = 32
        self.lr = 2e-3
        self.gamma = 0.997
        self.seq_len = 8            # stored sequence length
        self.burn_in = 2            # steps that only rebuild hidden state
        self.buffer_capacity = 2000  # sequences
        self.train_batch_size = 32
        self.episodes_per_iter = 16
        self.updates_per_iter = 4
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_iters = 40
        self.target_update_interval = 5
        self.max_episode_steps = 16
        self.seed = 0

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown R2D2 option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "R2D2":
        if not 0 <= self.burn_in < self.seq_len:
            raise ValueError(
                f"burn_in ({self.burn_in}) must be in [0, seq_len"
                f"={self.seq_len})")
        return R2D2({"r2d2_config": self})


class R2D2(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        cfg: R2D2Config = config.get("r2d2_config") or R2D2Config()
        self.cfg = cfg
        self.env = cfg.env_maker(cfg.seed)
        rng = np.random.default_rng(cfg.seed)
        self._np_rng = rng
        h, d, A = cfg.hidden, cfg.obs_dim, cfg.num_actions

        def glorot(m, n):
            return (rng.standard_normal((m, n)) *
                    np.sqrt(2.0 / (m + n))).astype(np.float32)

        self.params = jax.tree_util.tree_map(jnp.asarray, {
            "wxz": glorot(d, h), "whz": glorot(h, h), "bz": np.zeros(h, np.float32),
            "wxr": glorot(d, h), "whr": glorot(h, h), "br": np.zeros(h, np.float32),
            "wxn": glorot(d, h), "whn": glorot(h, h), "bn": np.zeros(h, np.float32),
            "wq": glorot(h, A), "bq": np.zeros(A, np.float32),
        })
        self.target = jax.device_get(self.params)
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        # sequence-major replay: each row is one [seq_len] slice
        self._sequences: List[dict] = []
        self._reward_hist: List[float] = []

        def gru_cell(p, hprev, x):
            z = jax.nn.sigmoid(x @ p["wxz"] + hprev @ p["whz"] + p["bz"])
            r = jax.nn.sigmoid(x @ p["wxr"] + hprev @ p["whr"] + p["br"])
            n = jnp.tanh(x @ p["wxn"] + (r * hprev) @ p["whn"] + p["bn"])
            return (1 - z) * n + z * hprev

        def q_seq(p, obs_seq, h0):
            """obs_seq [B,T,d], h0 [B,h] -> (q [B,T,A], h_T)."""
            def body(hc, x):
                hc = gru_cell(p, hc, x)
                return hc, hc

            hT, hs = jax.lax.scan(body, h0, obs_seq.swapaxes(0, 1))
            hs = hs.swapaxes(0, 1)                      # [B,T,h]
            return hs @ p["wq"] + p["bq"], hT

        self._gru_cell = gru_cell

        def loss_fn(p, tp, batch):
            B = batch["obs"].shape[0]
            h0 = jnp.zeros((B, h))
            # burn-in: rebuild recurrent state without gradients
            bi = cfg.burn_in
            _, h_start = q_seq(jax.lax.stop_gradient(p),
                               batch["obs"][:, :bi], h0)
            h_start = jax.lax.stop_gradient(h_start)
            _, ht_start = q_seq(tp, batch["obs"][:, :bi], h0)
            # one extended pass: [obs[bi:], final next_obs]. Since
            # next_obs[t] == obs[t+1], q_ext[:, 1:] are the next-state
            # values evaluated with the CORRECT (non-stale) hidden state.
            ext = jnp.concatenate(
                [batch["obs"][:, bi:], batch["next_obs"][:, -1:]], axis=1)
            q_ext, _ = q_seq(p, ext, h_start)           # [B,T'+1,A]
            q_taken = jnp.take_along_axis(
                q_ext[:, :-1], batch["actions"][:, bi:, None],
                axis=-1)[..., 0]
            # double DQN: online picks the argmax, target evaluates
            a_star = jnp.argmax(q_ext[:, 1:], axis=-1)
            q_ext_t, _ = q_seq(tp, ext, ht_start)
            next_q = jnp.take_along_axis(
                q_ext_t[:, 1:], a_star[..., None], axis=-1)[..., 0]
            target = batch["rewards"][:, bi:] + cfg.gamma * \
                (1 - batch["dones"][:, bi:]) * jax.lax.stop_gradient(next_q)
            mask = batch["mask"][:, bi:]
            td = (q_taken - target) * mask
            return (td ** 2).sum() / jnp.maximum(mask.sum(), 1.0)

        def update(p, opt_state, tp, batch):
            loss, grads = jax.value_and_grad(loss_fn)(p, tp, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state, p)
            return optax.apply_updates(p, updates), opt_state, loss

        def act_step(p, hc, x):
            hc = gru_cell(p, hc, x)
            return hc, hc @ p["wq"] + p["bq"]

        self._update = jax.jit(update)
        self._act_step = jax.jit(act_step)
        self._jax = jax
        self._jnp = jnp

    # ----------------------------------------------------------- rollouts
    def _epsilon(self) -> float:
        cfg = self.cfg
        frac = min(1.0, self.iteration / max(1, cfg.epsilon_decay_iters))
        return cfg.epsilon_start + frac * (cfg.epsilon_end - cfg.epsilon_start)

    def _collect_episode(self, epsilon: float, store: bool = True) -> float:
        cfg, jnp = self.cfg, self._jnp
        env = self.env
        obs = env.reset()
        hc = jnp.zeros((1, cfg.hidden))
        rows = {k: [] for k in ("obs", "actions", "rewards", "next_obs",
                                "dones")}
        total = 0.0
        for _ in range(cfg.max_episode_steps):
            hc, q = self._act_step(self.params, hc, jnp.asarray(obs[None]))
            if epsilon > 0 and self._np_rng.random() < epsilon:
                a = int(self._np_rng.integers(cfg.num_actions))
            else:
                a = int(np.asarray(q)[0].argmax())
            nxt, r, done, _ = env.step(a)
            rows["obs"].append(obs)
            rows["actions"].append(a)
            rows["rewards"].append(r)
            rows["next_obs"].append(nxt)
            rows["dones"].append(float(done))
            total += r
            obs = nxt
            if done:
                break
        if store:
            self._store_episode(rows)
        return total

    def _store_episode(self, rows: Dict[str, list]) -> None:
        """Chop the episode into fixed seq_len windows (zero-padded, with a
        validity mask) — R2D2's stored-sequence format."""
        cfg = self.cfg
        T = len(rows["actions"])
        for start in range(0, T, cfg.seq_len - cfg.burn_in or 1):
            end = min(start + cfg.seq_len, T)
            n = end - start
            seq = {
                "obs": np.zeros((cfg.seq_len, cfg.obs_dim), np.float32),
                "next_obs": np.zeros((cfg.seq_len, cfg.obs_dim), np.float32),
                "actions": np.zeros(cfg.seq_len, np.int32),
                "rewards": np.zeros(cfg.seq_len, np.float32),
                "dones": np.ones(cfg.seq_len, np.float32),
                "mask": np.zeros(cfg.seq_len, np.float32),
            }
            seq["obs"][:n] = rows["obs"][start:end]
            seq["next_obs"][:n] = rows["next_obs"][start:end]
            seq["actions"][:n] = rows["actions"][start:end]
            seq["rewards"][:n] = rows["rewards"][start:end]
            seq["dones"][:n] = rows["dones"][start:end]
            seq["mask"][:n] = 1.0
            self._sequences.append(seq)
            if start == 0 and end == T:
                break
        if len(self._sequences) > cfg.buffer_capacity:
            self._sequences = self._sequences[-cfg.buffer_capacity:]

    # --------------------------------------------------------------- train
    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        eps = self._epsilon()
        returns = [self._collect_episode(eps)
                   for _ in range(cfg.episodes_per_iter)]
        self._reward_hist.extend(returns)
        self._reward_hist = self._reward_hist[-200:]

        losses = []
        if len(self._sequences) >= cfg.train_batch_size:
            for _ in range(cfg.updates_per_iter):
                idx = self._np_rng.integers(0, len(self._sequences),
                                            cfg.train_batch_size)
                rows = [self._sequences[i] for i in idx]
                batch = {k: self._jnp.asarray(np.stack([r[k] for r in rows]))
                         for k in rows[0]}
                self.params, self.opt_state, loss = self._update(
                    self.params, self.opt_state, self.target, batch)
                losses.append(float(loss))
            if self.iteration % cfg.target_update_interval == 0:
                self.target = self._jax.device_get(self.params)
        return {
            "episode_reward_mean": float(np.mean(self._reward_hist)),
            "epsilon": eps,
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "num_sequences": len(self._sequences),
        }

    def greedy_return(self, episodes: int = 20) -> float:
        return float(np.mean([self._collect_episode(0.0, store=False)
                              for _ in range(episodes)]))

    def get_weights(self):
        return self._jax.device_get(self.params)

    def set_weights(self, weights) -> None:
        self.params = self._jax.tree_util.tree_map(self._jnp.asarray, weights)
        self.target = self._jax.device_get(self.params)
