"""A2C: synchronous advantage actor-critic.

Mirrors the reference's A2C (`rllib/algorithms/a2c/a2c.py`): the PPO
anatomy minus the surrogate clipping — one parallel sample round, GAE
advantages, a single on-policy gradient step per iteration. Reuses the PPO
rollout fleet (same actor, same policy net); the learner is one jitted
policy-gradient + value + entropy update.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import CartPoleEnv
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.ppo import RolloutWorker, compute_gae


class A2CLearner(Learner):
    """Single pg + vf + entropy update (no clipping, no epochs) on the
    Learner stack (reference A2C via core/learner); the network is a
    swappable RLModule. Pass `mesh=` to dp-shard batches."""

    def __init__(self, obs_dim: int, num_actions: int, lr: float,
                 vf_coeff: float = 0.5, entropy_coeff: float = 0.01,
                 seed: int = 0, mesh=None, module=None):
        from ray_tpu.rllib.rl_module import DiscreteActorCriticModule

        self.module = module or DiscreteActorCriticModule(obs_dim, num_actions)
        self._vf_coeff = vf_coeff
        self._entropy_coeff = entropy_coeff
        super().__init__(lr=lr, mesh=mesh, seed=seed)

    def init_params(self, seed: int):
        return self.module.init_params(seed)

    def loss(self, params, batch, extra, rng):
        out = self.module.forward_train(params, batch)
        dist = self.module.action_dist(out)
        logp = dist.logp(batch["actions"])
        pg = -(logp * batch["advantages"]).mean()
        vf = 0.5 * ((out["vf"] - batch["returns"]) ** 2).mean()
        entropy = dist.entropy().mean()
        total = pg + self._vf_coeff * vf - self._entropy_coeff * entropy
        return total, {"policy_loss": pg, "vf_loss": vf, "entropy": entropy}

    def update_once(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax

        aux = self.update(batch)
        return {k: float(v) for k, v in jax.device_get(aux).items()}


class A2CConfig:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: CartPoleEnv(seed)
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 4
        self.rollout_fragment_length = 32
        self.lr = 1e-3
        self.gamma = 0.99
        self.lambda_ = 1.0
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.seed = 0

    def environment(self, env_maker=None, *, obs_dim=None, num_actions=None):
        if env_maker is not None:
            self.env_maker = env_maker
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def rollouts(self, *, num_rollout_workers=None, num_envs_per_worker=None,
                 rollout_fragment_length=None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown A2C option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "A2C":
        return A2C({"a2c_config": self})


class A2C(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        cfg: A2CConfig = config.get("a2c_config") or A2CConfig()
        self.cfg = cfg
        self.learner = A2CLearner(
            cfg.obs_dim, cfg.num_actions, cfg.lr, cfg.vf_coeff,
            cfg.entropy_coeff, cfg.seed)
        self.workers = [
            RolloutWorker.options(num_cpus=1).remote(
                cfg.env_maker, cfg.num_envs_per_worker,
                cfg.seed + 1000 * (i + 1), cfg.obs_dim, cfg.num_actions)
            for i in range(cfg.num_rollout_workers)]
        self._broadcast_weights()
        self._reward_history: List[float] = []
        self._total_steps = 0

    def _broadcast_weights(self) -> None:
        from ray_tpu.rllib.learner import broadcast_weights

        broadcast_weights(self.learner.get_weights(), self.workers)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        samples = ray_tpu.get([
            wk.sample.remote(cfg.rollout_fragment_length) for wk in self.workers])
        flats, episode_returns = [], []
        for batch in samples:
            adv, ret = compute_gae(batch, cfg.gamma, cfg.lambda_)
            T, N = batch["actions"].shape
            flats.append({
                "obs": batch["obs"].reshape(T * N, -1),
                "actions": batch["actions"].reshape(-1),
                "advantages": adv.reshape(-1),
                "returns": ret.reshape(-1),
            })
            episode_returns.extend(batch["episode_returns"].tolist())
        flat = {k: np.concatenate([f[k] for f in flats]) for k in flats[0]}
        adv = flat["advantages"]
        flat["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        self._total_steps += int(flat["actions"].size)
        stats = self.learner.update_once(flat)
        self._broadcast_weights()
        if episode_returns:
            self._reward_history.extend(episode_returns)
            self._reward_history = self._reward_history[-100:]
        return {
            "episode_reward_mean": (float(np.mean(self._reward_history))
                                    if self._reward_history else 0.0),
            "num_env_steps_sampled": self._total_steps,
            **stats,
        }

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        self.learner.set_weights(weights)
        self._broadcast_weights()

    def stop(self) -> None:
        self._kill_workers(self.workers)
