"""RLModule: the swappable params + forward-functions unit of RLlib.

Mirrors the reference's `rllib/core/rl_module/rl_module.py`: an algorithm's
neural network is a MODULE — parameter initialization, the train/inference
forward passes, and the action distribution — separable from the update rule
(Learner) and from env plumbing (connectors). Swapping the architecture
means swapping the module; the learner's loss and the rollout loop don't
change.

TPU-first shape: modules are PURE-FUNCTION bundles over pytrees (init ->
params pytree; forwards are `f(params, obs)` usable under jit/grad/vmap AND
under plain numpy for CPU env-stepping actors), not stateful nn.Module
objects — the same functional seam `jax.jit` needs anyway.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ray_tpu.rllib.models import init_mlp, mlp_forward, mlp_hidden

__all__ = [
    "Categorical", "RLModule", "DiscreteActorCriticModule", "QModule",
]


# ------------------------------------------------------------ distributions


def _xp(arr):
    """numpy for numpy inputs, jax.numpy for traced/jax inputs — modules
    and distributions run in BOTH worlds (CPU rollout actors / jitted
    losses)."""
    if isinstance(arr, np.ndarray):
        return np
    import jax.numpy as jnp

    return jnp


class Categorical:
    """Action distribution over logits (reference TorchCategorical,
    rllib/models/distributions.py) — numpy-or-jax depending on input."""

    def __init__(self, logits):
        self.logits = logits

    def _log_probs(self):
        xp = _xp(self.logits)
        z = self.logits - self.logits.max(-1, keepdims=True)
        return z - xp.log(xp.exp(z).sum(-1, keepdims=True))

    def sample(self, rng: np.random.Generator) -> np.ndarray:
        """Host-side sampling for rollout actors (gumbel trick: one vector
        op per step instead of a per-env np.choice loop)."""
        logp = np.asarray(self._log_probs())
        g = rng.gumbel(size=logp.shape)
        return (logp + g).argmax(-1).astype(np.int32)

    def logp(self, actions):
        logp_all = self._log_probs()
        xp = _xp(logp_all)
        return xp.take_along_axis(
            logp_all, xp.asarray(actions)[..., None], axis=-1)[..., 0]

    def entropy(self):
        logp_all = self._log_probs()
        xp = _xp(logp_all)
        return -(xp.exp(logp_all) * logp_all).sum(-1)

    def argmax(self) -> np.ndarray:
        return np.asarray(self.logits).argmax(-1).astype(np.int32)


# ----------------------------------------------------------------- modules


class RLModule:
    """Base module contract (reference rl_module.py: `_forward_inference`,
    `_forward_train`, `get_initial_state`)."""

    def init_params(self, seed: int):
        raise NotImplementedError

    def forward_inference(self, params, obs) -> Dict[str, Any]:
        """Outputs needed to ACT (runs in rollout workers; must accept
        numpy params + obs and stay numpy)."""
        raise NotImplementedError

    def forward_train(self, params, batch) -> Dict[str, Any]:
        """Outputs needed by the learner's loss (jax, under jit/grad)."""
        raise NotImplementedError

    def action_dist(self, fwd_out: Dict[str, Any]):
        """Distribution over actions from forward outputs."""
        raise NotImplementedError


class DiscreteActorCriticModule(RLModule):
    """Two-head MLP: categorical policy + value baseline — the module under
    PPO / A2C / APPO / IMPALA / MARWIL (reference PPOTorchRLModule)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Tuple[int, ...] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init_params(self, seed: int) -> Dict[str, Any]:
        rng = np.random.default_rng(seed)
        params = init_mlp(rng, (self.obs_dim, *self.hidden))
        h = self.hidden[-1]
        params["w_pi"] = (rng.standard_normal((h, self.num_actions))
                          * 0.01).astype(np.float32)
        params["b_pi"] = np.zeros(self.num_actions, np.float32)
        params["w_v"] = rng.standard_normal((h, 1)).astype(np.float32)
        params["b_v"] = np.zeros(1, np.float32)
        return params

    def _apply(self, params, obs):
        x = mlp_hidden(params, obs, len(self.hidden))
        logits = x @ params["w_pi"] + params["b_pi"]
        value = (x @ params["w_v"] + params["b_v"])[..., 0]
        return logits, value

    def forward_inference(self, params, obs) -> Dict[str, Any]:
        logits, value = self._apply(params, obs)
        return {"action_dist_inputs": logits, "vf": value}

    def forward_train(self, params, batch) -> Dict[str, Any]:
        logits, value = self._apply(params, batch["obs"])
        return {"action_dist_inputs": logits, "vf": value}

    def action_dist(self, fwd_out) -> Categorical:
        return Categorical(fwd_out["action_dist_inputs"])


class QModule(RLModule):
    """Q-value MLP — the module under DQN / CQL (greedy/eps-greedy action
    selection lives in connectors, not here)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Tuple[int, ...] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init_params(self, seed: int) -> Dict[str, Any]:
        rng = np.random.default_rng(seed)
        return init_mlp(rng, (self.obs_dim, *self.hidden, self.num_actions),
                        final_scale=np.sqrt(2.0 / self.hidden[-1]))

    def _apply(self, params, obs):
        return mlp_forward(params, obs, len(self.hidden) + 1)

    def forward_inference(self, params, obs) -> Dict[str, Any]:
        return {"action_dist_inputs": self._apply(params, obs)}

    def forward_train(self, params, batch) -> Dict[str, Any]:
        return {"q": self._apply(params, batch["obs"]),
                "q_next": self._apply(params, batch["next_obs"])}

    def action_dist(self, fwd_out) -> Categorical:
        return Categorical(fwd_out["action_dist_inputs"])
