"""RLModule: the swappable params + forward-functions unit of RLlib.

Mirrors the reference's `rllib/core/rl_module/rl_module.py`: an algorithm's
neural network is a MODULE — parameter initialization, the train/inference
forward passes, and the action distribution — separable from the update rule
(Learner) and from env plumbing (connectors). Swapping the architecture
means swapping the module; the learner's loss and the rollout loop don't
change.

TPU-first shape: modules are PURE-FUNCTION bundles over pytrees (init ->
params pytree; forwards are `f(params, obs)` usable under jit/grad/vmap AND
under plain numpy for CPU env-stepping actors), not stateful nn.Module
objects — the same functional seam `jax.jit` needs anyway.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

from ray_tpu.rllib.models import init_mlp, mlp_forward, mlp_hidden

__all__ = [
    "Categorical", "SquashedGaussian", "Deterministic", "RLModule",
    "DiscreteActorCriticModule", "QModule", "SquashedGaussianModule",
    "DeterministicPolicyModule", "RecurrentQModule",
]


# ------------------------------------------------------------ distributions


def _xp(arr):
    """numpy for numpy inputs, jax.numpy for traced/jax inputs — modules
    and distributions run in BOTH worlds (CPU rollout actors / jitted
    losses)."""
    if isinstance(arr, np.ndarray):
        return np
    import jax.numpy as jnp

    return jnp


class Categorical:
    """Action distribution over logits (reference TorchCategorical,
    rllib/models/distributions.py) — numpy-or-jax depending on input."""

    def __init__(self, logits):
        self.logits = logits

    def _log_probs(self):
        xp = _xp(self.logits)
        z = self.logits - self.logits.max(-1, keepdims=True)
        return z - xp.log(xp.exp(z).sum(-1, keepdims=True))

    def sample(self, rng: np.random.Generator) -> np.ndarray:
        """Host-side sampling for rollout actors (gumbel trick: one vector
        op per step instead of a per-env np.choice loop)."""
        logp = np.asarray(self._log_probs())
        g = rng.gumbel(size=logp.shape)
        return (logp + g).argmax(-1).astype(np.int32)

    def logp(self, actions):
        logp_all = self._log_probs()
        xp = _xp(logp_all)
        return xp.take_along_axis(
            logp_all, xp.asarray(actions)[..., None], axis=-1)[..., 0]

    def entropy(self):
        logp_all = self._log_probs()
        xp = _xp(logp_all)
        return -(xp.exp(logp_all) * logp_all).sum(-1)

    def argmax(self) -> np.ndarray:
        return np.asarray(self.logits).argmax(-1).astype(np.int32)


class SquashedGaussian:
    """tanh-squashed diagonal Gaussian over `concat([mean, log_std])`
    inputs, scaled to [-max_action, max_action] (SAC's acting policy;
    reference rllib/models/tf/tf_distributions.py TfSquashedGaussian).
    The learner's reparameterized path keeps its own jax sampler (it
    needs the pre-squash value for the exact log-prob); this distribution
    serves the HOST-SIDE acting path."""

    LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0

    def __init__(self, inputs, max_action: float = 1.0):
        inputs = np.asarray(inputs)
        d = inputs.shape[-1] // 2
        self.mean = inputs[..., :d]
        self.log_std = np.clip(inputs[..., d:],
                               self.LOG_STD_MIN, self.LOG_STD_MAX)
        self.max_action = max_action

    def sample(self, rng: np.random.Generator) -> np.ndarray:
        pre = self.mean + np.exp(self.log_std) \
            * rng.standard_normal(self.mean.shape)
        return (np.tanh(pre) * self.max_action).astype(np.float32)

    def argmax(self) -> np.ndarray:
        """Mode: the squashed mean (evaluation-time deterministic act)."""
        return (np.tanh(self.mean) * self.max_action).astype(np.float32)

    def logp(self, actions) -> np.ndarray:
        """Change-of-variables log-prob; recovers the pre-squash value by
        atanh (clipped away from the +-1 boundary)."""
        a = np.clip(np.asarray(actions) / self.max_action,
                    -1.0 + 1e-6, 1.0 - 1e-6)
        pre = np.arctanh(a)
        std = np.exp(self.log_std)
        z = (pre - self.mean) / std
        logp = (-0.5 * (z ** 2 + 2 * self.log_std + np.log(2 * np.pi))).sum(-1)
        # d tanh(x)/dx = 1 - tanh(x)^2; stable softplus form
        logp -= (2 * (np.log(2.0) - pre
                      - np.logaddexp(0.0, -2.0 * pre))).sum(-1)
        return logp.astype(np.float32)

    def entropy(self) -> np.ndarray:
        """Pre-squash Gaussian entropy (the squash correction has no closed
        form; this is the standard surrogate)."""
        return (self.log_std + 0.5 * np.log(2 * np.pi * np.e)).sum(-1)


class Deterministic:
    """Point-mass distribution: DDPG/TD3 actors emit the action directly;
    exploration noise is a CONNECTOR, not part of the distribution
    (reference rllib/models/distributions.py Deterministic)."""

    def __init__(self, actions):
        self.actions = actions

    def sample(self, rng: np.random.Generator) -> np.ndarray:
        return np.asarray(self.actions, np.float32)

    def argmax(self) -> np.ndarray:
        return np.asarray(self.actions, np.float32)

    def logp(self, actions) -> np.ndarray:
        return np.zeros(np.asarray(actions).shape[:-1], np.float32)

    def entropy(self) -> np.ndarray:
        return np.zeros(np.asarray(self.actions).shape[:-1], np.float32)


# ----------------------------------------------------------------- modules


class RLModule:
    """Base module contract (reference rl_module.py: `_forward_inference`,
    `_forward_train`, `get_initial_state`)."""

    def init_params(self, seed: int):
        raise NotImplementedError

    def forward_inference(self, params, obs) -> Dict[str, Any]:
        """Outputs needed to ACT (runs in rollout workers; must accept
        numpy params + obs and stay numpy)."""
        raise NotImplementedError

    def forward_train(self, params, batch) -> Dict[str, Any]:
        """Outputs needed by the learner's loss (jax, under jit/grad)."""
        raise NotImplementedError

    def action_dist(self, fwd_out: Dict[str, Any]):
        """Distribution over actions from forward outputs."""
        raise NotImplementedError


class DiscreteActorCriticModule(RLModule):
    """Two-head MLP: categorical policy + value baseline — the module under
    PPO / A2C / APPO / IMPALA / MARWIL (reference PPOTorchRLModule)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Tuple[int, ...] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init_params(self, seed: int) -> Dict[str, Any]:
        rng = np.random.default_rng(seed)
        params = init_mlp(rng, (self.obs_dim, *self.hidden))
        h = self.hidden[-1]
        params["w_pi"] = (rng.standard_normal((h, self.num_actions))
                          * 0.01).astype(np.float32)
        params["b_pi"] = np.zeros(self.num_actions, np.float32)
        params["w_v"] = rng.standard_normal((h, 1)).astype(np.float32)
        params["b_v"] = np.zeros(1, np.float32)
        return params

    def _apply(self, params, obs):
        x = mlp_hidden(params, obs, len(self.hidden))
        logits = x @ params["w_pi"] + params["b_pi"]
        value = (x @ params["w_v"] + params["b_v"])[..., 0]
        return logits, value

    def forward_inference(self, params, obs) -> Dict[str, Any]:
        logits, value = self._apply(params, obs)
        return {"action_dist_inputs": logits, "vf": value}

    def forward_train(self, params, batch) -> Dict[str, Any]:
        logits, value = self._apply(params, batch["obs"])
        return {"action_dist_inputs": logits, "vf": value}

    def action_dist(self, fwd_out) -> Categorical:
        return Categorical(fwd_out["action_dist_inputs"])


class QModule(RLModule):
    """Q-value MLP — the module under DQN / CQL (greedy/eps-greedy action
    selection lives in connectors, not here)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Tuple[int, ...] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init_params(self, seed: int) -> Dict[str, Any]:
        rng = np.random.default_rng(seed)
        return init_mlp(rng, (self.obs_dim, *self.hidden, self.num_actions),
                        final_scale=np.sqrt(2.0 / self.hidden[-1]))

    def _apply(self, params, obs):
        # depth inferred from params (w0/b0 ... pairs), so a learner with a
        # different hidden stack than the module default still applies fully
        return mlp_forward(params, obs, len(params) // 2)

    def forward_inference(self, params, obs) -> Dict[str, Any]:
        return {"action_dist_inputs": self._apply(params, obs)}

    def forward_train(self, params, batch) -> Dict[str, Any]:
        return {"q": self._apply(params, batch["obs"]),
                "q_next": self._apply(params, batch["next_obs"])}

    def action_dist(self, fwd_out) -> Categorical:
        return Categorical(fwd_out["action_dist_inputs"])


class SquashedGaussianModule(RLModule):
    """Continuous stochastic actor: MLP -> concat(mean, log_std), squashed
    tanh-Gaussian — SAC's acting module (reference SACTorchRLModule). The
    SAC learner keeps its own jax reparameterized sampler over the SAME
    params; this module is the worker-side acting contract."""

    def __init__(self, obs_dim: int, action_dim: int, max_action: float,
                 hidden: Tuple[int, ...] = (256, 256)):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.max_action = max_action
        self.hidden = tuple(hidden)

    def init_params(self, seed: int) -> Dict[str, Any]:
        rng = np.random.default_rng(seed)
        return init_mlp(rng, (self.obs_dim, *self.hidden, 2 * self.action_dim),
                        final_scale=0.01)

    def forward_inference(self, params, obs) -> Dict[str, Any]:
        # depth from params, matching sac.actor_dist's len(params)//2 rule
        out = mlp_forward(params, obs, len(params) // 2)
        return {"action_dist_inputs": out}

    def forward_train(self, params, batch) -> Dict[str, Any]:
        return self.forward_inference(params, batch["obs"])

    def action_dist(self, fwd_out) -> SquashedGaussian:
        return SquashedGaussian(fwd_out["action_dist_inputs"],
                                self.max_action)


class DeterministicPolicyModule(RLModule):
    """Deterministic continuous actor: tanh(MLP) * max_action — the module
    under DDPG/TD3 (reference DDPGTorchModel); exploration noise is the
    GaussianNoise connector, not baked into the network."""

    def __init__(self, obs_dim: int, action_dim: int, max_action: float,
                 hidden: Tuple[int, ...] = (256, 256)):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.max_action = max_action
        self.hidden = tuple(hidden)

    def init_params(self, seed: int) -> Dict[str, Any]:
        rng = np.random.default_rng(seed)
        return init_mlp(rng, (self.obs_dim, *self.hidden, self.action_dim),
                        final_scale=0.01)

    def forward_inference(self, params, obs) -> Dict[str, Any]:
        out = mlp_forward(params, obs, len(params) // 2)
        xp = _xp(out)
        return {"action_dist_inputs": xp.tanh(out) * self.max_action}

    def forward_train(self, params, batch) -> Dict[str, Any]:
        return self.forward_inference(params, batch["obs"])

    def action_dist(self, fwd_out) -> Deterministic:
        return Deterministic(fwd_out["action_dist_inputs"])


class RecurrentQModule(RLModule):
    """GRU Q-network with EXPLICIT state in/out — the recurrent module
    R2D2 acts and trains through (reference rllib/algorithms/r2d2/
    r2d2_torch_policy.py; get_initial_state per rl_module.py). A GRU over
    LSTM: one gate fewer, same episodic memory, and all gates are two fused
    matmuls — friendlier to the MXU.

    Acting calls `forward_inference(params, obs, state=h)` one step at a
    time (numpy on env hosts); training calls `unroll` over [B, T]
    sequences (jax lax.scan under jit). Both run the SAME cell math."""

    def __init__(self, obs_dim: int, num_actions: int, hidden: int = 32):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = hidden

    def init_params(self, seed: int) -> Dict[str, Any]:
        rng = np.random.default_rng(seed)
        d, h, A = self.obs_dim, self.hidden, self.num_actions

        def glorot(m, n):
            return (rng.standard_normal((m, n))
                    * np.sqrt(2.0 / (m + n))).astype(np.float32)

        return {
            "wxz": glorot(d, h), "whz": glorot(h, h),
            "bz": np.zeros(h, np.float32),
            "wxr": glorot(d, h), "whr": glorot(h, h),
            "br": np.zeros(h, np.float32),
            "wxn": glorot(d, h), "whn": glorot(h, h),
            "bn": np.zeros(h, np.float32),
            "wq": glorot(h, A), "bq": np.zeros(A, np.float32),
        }

    def get_initial_state(self, batch_size: int = 1) -> np.ndarray:
        return np.zeros((batch_size, self.hidden), np.float32)

    def _cell(self, params, h, x):
        """One GRU step — numpy or jax by input type."""
        xp = _xp(x)

        def sigmoid(v):
            return 1.0 / (1.0 + xp.exp(-v))

        z = sigmoid(x @ params["wxz"] + h @ params["whz"] + params["bz"])
        r = sigmoid(x @ params["wxr"] + h @ params["whr"] + params["br"])
        n = xp.tanh(x @ params["wxn"] + (r * h) @ params["whn"]
                    + params["bn"])
        return (1 - z) * n + z * h

    def forward_inference(self, params, obs, state=None) -> Dict[str, Any]:
        if state is None:
            state = self.get_initial_state(len(obs))
        h = self._cell(params, state, obs)
        return {"action_dist_inputs": h @ params["wq"] + params["bq"],
                "state_out": h}

    def unroll(self, params, obs_seq, h0):
        """obs_seq [B, T, d], h0 [B, h] -> (q [B, T, A], h_T). jax-only
        (training path; per-tick outputs stream as scan ys, never carry)."""
        import jax
        import jax.numpy as jnp

        def body(hc, x):
            hc = self._cell(params, hc, x)
            return hc, hc

        hT, hs = jax.lax.scan(body, h0, obs_seq.swapaxes(0, 1))
        hs = hs.swapaxes(0, 1)                       # [B, T, h]
        return hs @ params["wq"] + params["bq"], hT

    def forward_train(self, params, batch) -> Dict[str, Any]:
        import jax.numpy as jnp

        h0 = batch.get("state_in")
        if h0 is None:
            h0 = jnp.zeros((batch["obs"].shape[0], self.hidden))
        q, hT = self.unroll(params, batch["obs"], h0)
        return {"action_dist_inputs": q, "state_out": hT}

    def action_dist(self, fwd_out) -> Categorical:
        return Categorical(fwd_out["action_dist_inputs"])
