"""Learner / LearnerGroup: the mesh-native RL update stack.

Mirrors the reference's new training stack (`rllib/core/learner/learner.py:100`
— `compute_gradients:409`, `update:773` — and `learner_group.py:52`), built
TPU-first instead of DDP-first:

* `Learner` owns one module's params + optimizer and compiles a SINGLE
  jitted update. Given a `jax.sharding.Mesh` it shards the batch over the
  mesh's `dp` axis with replicated params — GSPMD inserts the gradient
  all-reduce, so the "distributed data parallel learner" is one XLA program
  whose collectives ride ICI/DCN, not a fleet of gradient-synchronizing
  processes.
* `LearnerGroup` scales a Learner out: `backend="mesh"` (default, the
  TPU-idiomatic path) is one process driving the sharded update; and
  `backend="actors"` runs N learner actors (CPU hosts) that all-reduce
  gradients through `ray_tpu.util.collective`'s host backend — the analog
  of the reference's gloo/NCCL learner workers for envs without a mesh.

Subclass contract: implement `init_params(seed)` and
`loss(params, batch, extra, rng) -> (loss, aux_metrics_dict)` (`rng` is a
fresh PRNG key per update for stochastic losses); optionally maintain
`extra` state (e.g. a target network) via `make_extra()` and the jitted
`post_update(params, extra)` hook (polyak syncs), and override
`make_optimizer()` for per-submodule optimizers (`optax.multi_transform`,
`delayed` for TD3-style update periods).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu

logger = logging.getLogger(__name__)

__all__ = ["Learner", "LearnerGroup", "broadcast_weights", "delayed"]


def broadcast_weights(weights, handles, method: str = "set_weights"):
    """Fan a weights pytree out to worker actors as ONE plasma object with
    an owner-directed push broadcast (`ray_tpu.push`, reference
    push_manager.h:29): N workers on other nodes read a pre-pushed local
    copy instead of N pulls serializing on this owner. Small (inlined)
    weights skip the push. Blocks until every worker applied them."""
    ref = ray_tpu.put(weights)
    try:
        ray_tpu.push(ref)
    except ValueError:
        pass  # inlined small object: nothing to push, args ship it inline
    except Exception:
        # push is an optimization; the pull path still works
        logging.getLogger(__name__).debug("weight push failed", exc_info=True)
    return ray_tpu.get([getattr(h, method).remote(ref) for h in handles])


def delayed(tx, period: int):
    """Wrap an optax transform so it applies only every `period`-th step,
    with its inner state FROZEN on skipped steps (true delayed updates —
    zeroing gradients instead would still decay Adam's moments). This is how
    TD3's delayed actor rides a single jitted update: compose under
    `optax.multi_transform({"actor": delayed(adam, d), ...})`."""
    import jax
    import jax.numpy as jnp
    import optax

    def init(params):
        return (tx.init(params), jnp.zeros((), jnp.int32))

    def update(grads, state, params=None):
        inner, count = state

        def run(_):
            return tx.update(grads, inner, params)

        def skip(_):
            return jax.tree_util.tree_map(jnp.zeros_like, grads), inner

        updates, inner2 = jax.lax.cond(count % period == 0, run, skip, None)
        return updates, (inner2, count + 1)

    return optax.GradientTransformation(init, update)


class Learner:
    def __init__(self, *, lr: float = 1e-3, optimizer=None, mesh=None,
                 seed: int = 0):
        import jax
        import optax

        self.mesh = mesh
        self._lr = lr
        self.optimizer = (optimizer if optimizer is not None
                          else self.make_optimizer())
        self.params = self.init_params(seed)
        self.opt_state = self.optimizer.init(self.params)
        self._rng_key = jax.random.PRNGKey(seed)
        self._build(jax, optax)

    # ------------------------------------------------------ subclass hooks
    def init_params(self, seed: int):
        raise NotImplementedError

    def loss(self, params, batch, extra, rng):
        """Return (scalar_loss, aux_metrics_dict). `rng` is a fresh PRNG key
        per update (stochastic losses: target smoothing, reparameterized
        sampling); deterministic losses just ignore it."""
        raise NotImplementedError

    def make_optimizer(self):
        """Optax transform for the whole params pytree. Override for
        per-submodule optimizers via `optax.multi_transform` (the moral
        equivalent of the reference's configure_optimizers_for_module,
        learner.py:253) — see `delayed()` for TD3-style update periods."""
        import optax

        return optax.adam(self._lr)

    def make_extra(self):
        """Extra (non-optimized) pytree threaded through the update, e.g. a
        target network. None by default."""
        return None

    def post_update(self, params, extra):
        """Jitted hook after the optimizer step: return the next `extra`
        (e.g. polyak target sync — the reference's
        additional_update_for_module). Default: unchanged."""
        return extra

    # ------------------------------------------------------------- compile
    def _build(self, jax, optax) -> None:
        def grad_fn(params, extra, rng, batch):
            (l, aux), grads = jax.value_and_grad(
                self.loss, has_aux=True)(params, batch, extra, rng)
            aux = dict(aux)
            aux["total_loss"] = l
            return grads, aux

        def update_fn(params, opt_state, extra, rng, batch):
            grads, aux = grad_fn(params, extra, rng, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            extra = self.post_update(params, extra)
            return params, opt_state, extra, aux

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(self.mesh, P())
            batch_sh = NamedSharding(self.mesh, P(self.batch_shard_axis))
            self._update_fn = jax.jit(
                update_fn,
                in_shardings=(repl, repl, repl, repl, batch_sh),
                out_shardings=(repl, repl, repl, repl))
            self._grad_fn = jax.jit(
                grad_fn,
                in_shardings=(repl, repl, repl, batch_sh),
                out_shardings=(repl, repl))
        else:
            self._update_fn = jax.jit(update_fn)
            self._grad_fn = jax.jit(grad_fn)
        self.extra = self.make_extra()

    # sharded batch layout: leading axis splits over this mesh axis —
    # sample-major losses use "dp" on axis 0; sequence losses (vtrace)
    # store batches batch-major [N, T] so dp still splits SAMPLES
    batch_shard_axis = "dp"

    def _fit_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Meshed updates need the leading dim divisible by dp: trim the
        ragged tail (standard RL practice for remainder minibatches) rather
        than crash on GSPMD's divisibility requirement."""
        if self.mesh is None:
            return batch
        dp = self.mesh.shape.get("dp", 1)
        n = len(next(iter(batch.values())))
        r = n % dp
        if r == 0:
            return batch
        if n < dp:
            # wrap-pad tiny batches up to dp (mirrors the actor backend's
            # shard padding) — a ragged SGD tail must not crash training
            import numpy as np

            idx = np.arange(dp) % n
            return {k: v[idx] for k, v in batch.items()}
        return {k: v[:n - r] for k, v in batch.items()}

    def _next_rng(self):
        import jax

        self._rng_key, sub = jax.random.split(self._rng_key)
        return sub

    # -------------------------------------------------------------- update
    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """One optimizer step on `batch` (sharded over dp when meshed);
        returns aux metrics (reference Learner.update:773)."""
        batch = self._fit_batch(batch)
        self.params, self.opt_state, self.extra, aux = self._update_fn(
            self.params, self.opt_state, self.extra, self._next_rng(), batch)
        return aux

    def compute_gradients(self, batch: Dict[str, np.ndarray]):
        """(grads, aux) without applying (reference compute_gradients:409)."""
        return self._grad_fn(self.params, self.extra, self._next_rng(),
                             self._fit_batch(batch))

    def apply_gradients(self, grads) -> None:
        import optax

        updates, self.opt_state = self.optimizer.update(
            grads, self.opt_state, self.params)
        self.params = optax.apply_updates(self.params, updates)

    # ------------------------------------------------------------- weights
    def get_weights(self):
        """Host copy of the params pytree (any nesting, not just flat
        dicts)."""
        import jax

        return jax.tree_util.tree_map(np.asarray, jax.device_get(self.params))

    def set_weights(self, weights) -> None:
        import jax
        import jax.numpy as jnp

        self.params = jax.tree_util.tree_map(jnp.asarray, weights)
        self.opt_state = self.optimizer.init(self.params)


@ray_tpu.remote
class _LearnerActor:
    """One member of an actor-backed LearnerGroup: computes gradients
    locally and all-reduces them through the host collective backend
    (reference learner workers with gloo DDP)."""

    def __init__(self, learner_blob: bytes, kwargs: dict,
                 world_size: int, rank: int, group_name: str):
        import cloudpickle

        cls = cloudpickle.loads(learner_blob)
        self._learner: Learner = cls(**kwargs)
        self._world = world_size
        self._rank = rank
        self._group = group_name
        if world_size > 1:
            from ray_tpu.util import collective

            collective.init_collective_group(
                world_size, rank, backend="host", group_name=group_name)

    def update_shard(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax

        grads, aux = self._learner.compute_gradients(batch)
        if self._world > 1:
            from ray_tpu.util import collective

            flat, tree = jax.tree_util.tree_flatten(grads)
            summed = [collective.allreduce(np.asarray(g), self._group)
                      / self._world for g in flat]
            grads = jax.tree_util.tree_unflatten(tree, summed)
        self._learner.apply_gradients(grads)
        return {k: float(v) for k, v in jax.device_get(aux).items()
                if np.ndim(v) == 0}

    def get_weights(self):
        return self._learner.get_weights()

    def set_weights(self, weights) -> bool:
        self._learner.set_weights(weights)
        return True


class LearnerGroup:
    """Scale a Learner to many devices/processes
    (reference learner_group.py:52)."""

    def __init__(self, learner_cls: Callable[..., Learner],
                 learner_kwargs: Optional[dict] = None, *,
                 backend: str = "mesh",
                 mesh=None,
                 num_learners: int = 1,
                 scheduling=None):
        self.backend = backend
        kwargs = dict(learner_kwargs or {})
        if backend == "mesh":
            if mesh is None:
                from ray_tpu.parallel import MeshConfig, make_mesh

                mesh = make_mesh(MeshConfig(dp=-1, fsdp=1, tp=1, sp=1))
            kwargs["mesh"] = mesh
            self.mesh = mesh
            self._learner = learner_cls(**kwargs)
            self._actors: List[Any] = []
        elif backend == "actors":
            import cloudpickle
            import uuid

            self.mesh = None
            self._learner = None
            blob = cloudpickle.dumps(learner_cls)
            # uuid, NOT id(self): a GC'd group's id can be reused and would
            # collide with the previous group's named rendezvous actor
            group = f"learner-group-{uuid.uuid4().hex[:12]}"
            self._group_name = group
            opts: dict = {}
            if scheduling is not None:
                opts["scheduling_strategy"] = scheduling
            actor_cls = (_LearnerActor.options(**opts)
                         if opts else _LearnerActor)
            self._actors = [
                actor_cls.remote(blob, kwargs, num_learners, rank, group)
                for rank in range(num_learners)]
            # materialize construction errors early
            ray_tpu.get([a.get_weights.remote() for a in self._actors])
        else:
            raise ValueError(f"unknown LearnerGroup backend {backend!r}")

    @property
    def num_learners(self) -> int:
        return len(self._actors) if self._actors else 1

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One synchronized update across the group: mesh backend shards the
        batch over dp inside jit; actor backend splits it across learners
        which all-reduce gradients."""
        if self._learner is not None:
            import jax

            aux = self._learner.update(batch)
            return {k: float(v) for k, v in jax.device_get(aux).items()
                    if np.ndim(v) == 0}
        n = len(self._actors)
        size = len(next(iter(batch.values())))
        # Wrap-pad so every sample trains and every rank gets a non-empty
        # shard (all ranks MUST participate in the all-reduce; an empty
        # shard would also mean NaN means).
        idx = np.arange(size)
        pad = (-size) % n
        if pad:
            idx = np.concatenate([idx, idx[:pad]])
        per = len(idx) // n
        shards = [{k: v[idx[i * per:(i + 1) * per]] for k, v in batch.items()}
                  for i in range(n)]
        stats = ray_tpu.get([a.update_shard.remote(s)
                             for a, s in zip(self._actors, shards)])
        return {k: float(np.mean([s[k] for s in stats]))
                for k in stats[0]} if stats else {}

    def update_minibatches(self, flat: Dict[str, np.ndarray],
                           num_epochs: int, minibatch_size: int,
                           rng: np.random.Generator) -> Dict[str, float]:
        """Epoch/shuffle/minibatch SGD driven through group update()s —
        one loop serving both backends (reference LearnerGroup.update with
        minibatching)."""
        n = len(next(iter(flat.values())))
        stats: Dict[str, float] = {}
        for _ in range(num_epochs):
            idx = rng.permutation(n)
            for start in range(0, n, minibatch_size):
                mb = {k: v[idx[start:start + minibatch_size]]
                      for k, v in flat.items()}
                stats = self.update(mb)
        return stats

    def get_weights(self) -> Dict[str, np.ndarray]:
        if self._learner is not None:
            return self._learner.get_weights()
        return ray_tpu.get(self._actors[0].get_weights.remote())

    def set_weights(self, weights: Dict[str, np.ndarray]) -> None:
        if self._learner is not None:
            self._learner.set_weights(weights)
        else:
            broadcast_weights(weights, self._actors)

    def shutdown(self) -> None:
        """Tear down learner actors + the collective rendezvous (the group
        does not auto-clean: like the reference's LearnerGroup.shutdown)."""
        if self._actors:
            if len(self._actors) > 1:
                try:
                    from ray_tpu.util import collective

                    collective.destroy_collective_group(self._group_name)
                except (ValueError, KeyError, ConnectionError) as e:
                    logger.debug("collective group already gone: %s", e)
            from ray_tpu.rllib.algorithm import Algorithm

            Algorithm._kill_workers(self._actors)
            self._actors = []
