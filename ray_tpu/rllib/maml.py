"""MAML: model-agnostic meta-learning (Finn et al. 2017).

Reference parity: rllib/algorithms/maml/ (SURVEY §2.3 algorithm list). The
reference meta-trains a policy over a distribution of RL tasks; this build
keeps MAML's actual algorithmic core — differentiating through K inner
SGD steps so the meta-update improves post-adaptation performance — as a
first-class JAX program (`jax.grad` through `jax.grad`, something the
torch reference needs higher-order autograd plumbing for), exercised on
the canonical sinusoid-regression task distribution. The task API
(`sample_tasks` / per-task support+query batches) is what an env-backed
meta-RL task set plugs into.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.models import init_mlp, mlp_forward


class SinusoidTasks:
    """Task distribution: y = A sin(x + phi), A~U[0.1,5], phi~U[0,pi]
    (the MAML paper's few-shot regression benchmark)."""

    def __init__(self, seed: int = 0):
        self.rng = np.random.default_rng(seed)

    def sample_tasks(self, n: int) -> List[Dict[str, float]]:
        return [{"amp": float(self.rng.uniform(0.1, 5.0)),
                 "phase": float(self.rng.uniform(0, np.pi))}
                for _ in range(n)]

    def sample_batch(self, task: Dict[str, float],
                     k: int) -> Tuple[np.ndarray, np.ndarray]:
        x = self.rng.uniform(-5, 5, (k, 1)).astype(np.float32)
        y = (task["amp"] * np.sin(x + task["phase"])).astype(np.float32)
        return x, y


class MAMLConfig:
    def __init__(self):
        self.inner_lr = 0.01
        self.outer_lr = 1e-3
        self.inner_steps = 1
        self.k_shot = 10
        self.meta_batch_size = 8
        self.hidden = (40, 40)
        self.seed = 0
        self.tasks: Any = None  # defaults to SinusoidTasks

    def training(self, **kw) -> "MAMLConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown option {k!r}")
            if v is not None:
                setattr(self, k, v)
        return self

    def build(self) -> "MAML":
        return MAML({"maml_config": self})


class MAML(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        cfg: MAMLConfig = config.get("maml_config") or MAMLConfig()
        self.cfg = cfg
        self.tasks = cfg.tasks or SinusoidTasks(cfg.seed)
        rng = np.random.default_rng(cfg.seed)
        sizes = (1, *cfg.hidden, 1)
        self.params = init_mlp(rng, sizes)
        self.n_layers = len(sizes) - 1
        self.optimizer = optax.adam(cfg.outer_lr)
        self.opt_state = self.optimizer.init(self.params)
        n_layers, inner_lr, inner_steps = (
            self.n_layers, cfg.inner_lr, cfg.inner_steps)

        def mse(params, x, y):
            pred = mlp_forward(params, x, n_layers)
            return ((pred - y) ** 2).mean()

        def adapt(params, x_s, y_s):
            """K inner SGD steps — differentiable, so the outer grad flows
            through the adaptation."""
            for _ in range(inner_steps):
                g = jax.grad(mse)(params, x_s, y_s)
                params = jax.tree_util.tree_map(
                    lambda p, gi: p - inner_lr * gi, params, g)
            return params

        def meta_loss(params, batch):
            # batch: x_s/y_s [T,k,1] support, x_q/y_q [T,k,1] query
            def task_loss(x_s, y_s, x_q, y_q):
                return mse(adapt(params, x_s, y_s), x_q, y_q)

            return jax.vmap(task_loss)(
                batch["x_s"], batch["y_s"],
                batch["x_q"], batch["y_q"]).mean()

        def update(params, opt_state, batch):
            loss, grads = jax.value_and_grad(meta_loss)(params, batch)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update = jax.jit(update)
        self._adapt = jax.jit(adapt)
        self._mse = jax.jit(mse)

    def _meta_batch(self) -> Dict[str, np.ndarray]:
        cfg = self.cfg
        tasks = self.tasks.sample_tasks(cfg.meta_batch_size)
        cols = {k: [] for k in ("x_s", "y_s", "x_q", "y_q")}
        for t in tasks:
            x_s, y_s = self.tasks.sample_batch(t, cfg.k_shot)
            x_q, y_q = self.tasks.sample_batch(t, cfg.k_shot)
            cols["x_s"].append(x_s)
            cols["y_s"].append(y_s)
            cols["x_q"].append(x_q)
            cols["y_q"].append(y_q)
        return {k: np.stack(v) for k, v in cols.items()}

    def training_step(self) -> Dict[str, Any]:
        losses = []
        for _ in range(20):
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state, self._meta_batch())
            losses.append(float(loss))
        return {"meta_loss": float(np.mean(losses))}

    def adaptation_loss(self, n_tasks: int = 20,
                        adapted: bool = True) -> float:
        """Mean query loss over fresh tasks, with (True) or without (False)
        the K-step inner adaptation — the gap is what MAML buys."""
        cfg = self.cfg
        losses = []
        for t in self.tasks.sample_tasks(n_tasks):
            x_s, y_s = self.tasks.sample_batch(t, cfg.k_shot)
            x_q, y_q = self.tasks.sample_batch(t, cfg.k_shot)
            params = (self._adapt(self.params, x_s, y_s)
                      if adapted else self.params)
            losses.append(float(self._mse(params, x_q, y_q)))
        return float(np.mean(losses))

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, jax.device_get(self.params))

    def set_weights(self, weights) -> None:
        self.params = weights
        self.opt_state = self.optimizer.init(self.params)
