"""PPO: rollout-worker actor fleet + JAX learner.

Mirrors the reference's PPO anatomy (SURVEY §3.6): `training_step` =
parallel `RolloutWorker.sample` actor calls -> concat to a train batch ->
learner update -> weight broadcast (`rllib/algorithms/algorithm.py:1336`,
`rollout_worker.py:879`, `core/learner/learner.py:409,773`). The learner is
TPU-native: a jitted clipped-surrogate update with minibatched SGD epochs
(pmap/mesh-ready — the policy step is pure JAX); rollout workers run
CPU envs as actors, exactly the reference's split of env hosts vs learner
chips.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import CartPoleEnv, VectorEnv
from ray_tpu.rllib.evaluation import EvalConfigMixin
from ray_tpu.rllib.learner import Learner


# ------------------------------------------------------------- policy model


from ray_tpu.rllib.models import init_mlp, mlp_hidden


def init_policy_params(rng_seed: int, obs_dim: int, num_actions: int,
                       hidden: Tuple[int, ...] = (64, 64)) -> Dict[str, Any]:
    rng = np.random.default_rng(rng_seed)
    params = init_mlp(rng, (obs_dim, *hidden))
    params["w_pi"] = (rng.standard_normal((hidden[-1], num_actions)) * 0.01).astype(np.float32)
    params["b_pi"] = np.zeros(num_actions, np.float32)
    params["w_v"] = (rng.standard_normal((hidden[-1], 1)) * 1.0).astype(np.float32)
    params["b_v"] = np.zeros(1, np.float32)
    return params


def policy_apply(params, obs, n_hidden: int = 2):
    """Returns (logits, value). Works under numpy AND jax.numpy."""
    x = mlp_hidden(params, obs, n_hidden)
    logits = x @ params["w_pi"] + params["b_pi"]
    value = (x @ params["w_v"] + params["b_v"])[..., 0]
    return logits, value


# ---------------------------------------------------------------- rollouts


class RolloutWorkerImpl:
    """Env-stepping actor (reference rollout_worker.py:166; `sample:879`).

    Acting is MODULE + CONNECTORS (reference EnvRunner + connector
    pipelines): the worker owns an RLModule and two pipelines —
    env_to_module preprocesses observations, module_to_env turns forward
    outputs into env actions. Exploration/postprocessing changes are
    pipeline edits, not worker forks."""

    def __init__(self, env_maker, num_envs: int, seed: int,
                 obs_dim: int, num_actions: int,
                 module=None, env_to_module=None, module_to_env=None):
        from ray_tpu.rllib.connectors import (CastObsFloat32,
                                              ConnectorPipeline, SampleAction)
        from ray_tpu.rllib.rl_module import DiscreteActorCriticModule

        self.vec = VectorEnv(env_maker, num_envs, seed)
        self.obs = self.vec.reset()
        self.rng = np.random.default_rng(seed)
        self.params: Optional[dict] = None
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.module = module or DiscreteActorCriticModule(obs_dim, num_actions)
        self.env_to_module = env_to_module or ConnectorPipeline(
            [CastObsFloat32()])
        self.module_to_env = module_to_env or ConnectorPipeline(
            [SampleAction()])
        self._timestep = 0
        # per-env running episode returns for metrics
        self._ep_returns = np.zeros(num_envs, np.float32)
        self._completed: List[float] = []

    def set_weights(self, params: dict) -> bool:
        self.params = {k: np.asarray(v) for k, v in params.items()}
        return True

    def eval_episodes(self, num_episodes: int, seed: int = 0):
        """Deterministic evaluation on a FRESH env (training episode state
        untouched) — reference Algorithm.evaluate's worker-side role."""
        from ray_tpu.rllib.evaluation import run_eval_episodes

        return run_eval_episodes(self.vec.env_maker, self.module,
                                 self.params, num_episodes, seed)

    def _act(self) -> Dict[str, Any]:
        data = {"obs": self.obs, "rng": self.rng, "module": self.module,
                "params": self.params, "timestep": self._timestep}
        data = self.env_to_module(data)
        data["fwd_out"] = self.module.forward_inference(self.params,
                                                        data["obs"])
        return self.module_to_env(data)

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Collect num_steps transitions per env; returns flat arrays plus
        bootstrap values for GAE."""
        assert self.params is not None, "set_weights before sample"
        T, N = num_steps, self.vec.num_envs
        obs_buf = np.zeros((T, N, self.obs_dim), np.float32)
        act_buf = np.zeros((T, N), np.int32)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        for t in range(T):
            data = self._act()
            obs_buf[t] = data["obs"]
            act_buf[t] = data["actions"]
            logp_buf[t] = data.get("logp", 0.0)
            val_buf[t] = np.asarray(data["fwd_out"]["vf"], np.float32)
            self.obs, rewards, dones, _ = self.vec.step(data["actions"])
            self._timestep += N
            rew_buf[t] = rewards
            done_buf[t] = dones
            self._ep_returns += rewards
            for i, d in enumerate(dones):
                if d:
                    self._completed.append(float(self._ep_returns[i]))
                    self._ep_returns[i] = 0.0
        last_value = self.module.forward_inference(
            self.params, np.asarray(self.obs, np.float32))["vf"]
        episode_returns, self._completed = self._completed, []
        return {
            "obs": obs_buf, "actions": act_buf, "logp": logp_buf,
            "values": val_buf, "rewards": rew_buf, "dones": done_buf,
            "last_value": np.asarray(last_value),
            "episode_returns": np.array(episode_returns, np.float32),
        }


# the remote actor form (plain impl kept importable so subclasses — A3C's
# gradient-computing worker — can extend the sample loop)
RolloutWorker = ray_tpu.remote(RolloutWorkerImpl)


def compute_gae(batch: Dict[str, np.ndarray], gamma: float, lam: float):
    """Generalized advantage estimation over [T, N] arrays."""
    rewards, values, dones = batch["rewards"], batch["values"], batch["dones"]
    T, N = rewards.shape
    adv = np.zeros((T, N), np.float32)
    last_gae = np.zeros(N, np.float32)
    next_value = batch["last_value"]
    for t in reversed(range(T)):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    returns = adv + values
    return adv, returns


# ----------------------------------------------------------------- learner


class PPOLearner(Learner):
    """Jitted clipped-surrogate update on the Learner stack (reference
    core/learner/learner.py); the network is a swappable RLModule
    (reference PPOTorchRLModule). Pass `mesh=` to shard minibatches over
    the dp axis with XLA-inserted gradient all-reduce (LearnerGroup mesh
    backend)."""

    def __init__(self, obs_dim: int, num_actions: int, lr: float,
                 clip: float = 0.2, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.01, seed: int = 0, mesh=None,
                 module=None):
        from ray_tpu.rllib.rl_module import DiscreteActorCriticModule

        self.module = module or DiscreteActorCriticModule(obs_dim, num_actions)
        self._clip = clip
        self._vf_coeff = vf_coeff
        self._entropy_coeff = entropy_coeff
        super().__init__(lr=lr, mesh=mesh, seed=seed)

    def init_params(self, seed: int):
        return self.module.init_params(seed)

    def loss(self, params, batch, extra, rng):
        import jax.numpy as jnp

        out = self.module.forward_train(params, batch)
        dist = self.module.action_dist(out)
        logp = dist.logp(batch["actions"])
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["advantages"]
        pg = -jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - self._clip, 1 + self._clip) * adv).mean()
        vf = 0.5 * ((out["vf"] - batch["returns"]) ** 2).mean()
        entropy = dist.entropy().mean()
        total = pg + self._vf_coeff * vf - self._entropy_coeff * entropy
        return total, {"policy_loss": pg, "vf_loss": vf, "entropy": entropy}

    def update_minibatches(self, flat: Dict[str, np.ndarray],
                           num_epochs: int, minibatch_size: int,
                           rng: np.random.Generator) -> Dict[str, float]:
        import jax

        n = len(flat["obs"])
        stats: Dict[str, Any] = {}
        for _ in range(num_epochs):
            idx = rng.permutation(n)
            for start in range(0, n, minibatch_size):
                mb = {k: v[idx[start:start + minibatch_size]] for k, v in flat.items()}
                stats = self.update(mb)
        return {k: float(v) for k, v in jax.device_get(stats).items()}


# --------------------------------------------------------------- algorithm


class PPOConfig(EvalConfigMixin):
    """Builder-pattern config (reference rllib/algorithms/ppo/ppo.py)."""

    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: CartPoleEnv(seed)
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 4
        self.rollout_fragment_length = 128
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.entropy_coeff = 0.01
        self.vf_coeff = 0.5
        self.num_sgd_iter = 4
        self.sgd_minibatch_size = 256
        self.seed = 0
        # LearnerGroup scaling (reference AlgorithmConfig.resources /
        # learner settings): backend None = plain local learner;
        # "mesh" = one jitted update dp-sharded over a Mesh;
        # "actors" = num_learners gradient-allreducing learner actors.
        self.learner_backend: Optional[str] = None
        self.num_learners = 1
        self.learner_mesh = None

    def environment(self, env_maker=None, *, obs_dim=None, num_actions=None) -> "PPOConfig":
        if env_maker is not None:
            self.env_maker = env_maker
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def rollouts(self, *, num_rollout_workers=None, num_envs_per_worker=None,
                 rollout_fragment_length=None) -> "PPOConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr=None, gamma=None, lambda_=None, clip_param=None,
                 entropy_coeff=None, num_sgd_iter=None,
                 sgd_minibatch_size=None) -> "PPOConfig":
        for k, v in [("lr", lr), ("gamma", gamma), ("lambda_", lambda_),
                     ("clip_param", clip_param), ("entropy_coeff", entropy_coeff),
                     ("num_sgd_iter", num_sgd_iter),
                     ("sgd_minibatch_size", sgd_minibatch_size)]:
            if v is not None:
                setattr(self, k, v)
        return self

    def learners(self, *, backend=None, num_learners=None,
                 mesh=None) -> "PPOConfig":
        """Scale the update with a LearnerGroup (reference
        AlgorithmConfig.learners): backend "mesh" or "actors"."""
        if backend is not None:
            self.learner_backend = backend
        if num_learners is not None:
            self.num_learners = num_learners
        if mesh is not None:
            self.learner_mesh = mesh
        return self

    def build(self) -> "PPO":
        return PPO({"ppo_config": self})


class PPO(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        cfg: PPOConfig = config.get("ppo_config") or PPOConfig()
        self.cfg = cfg
        lk = dict(obs_dim=cfg.obs_dim, num_actions=cfg.num_actions,
                  lr=cfg.lr, clip=cfg.clip_param, vf_coeff=cfg.vf_coeff,
                  entropy_coeff=cfg.entropy_coeff, seed=cfg.seed)
        self.learner_group = None
        if cfg.learner_backend is not None:
            from ray_tpu.rllib.learner import LearnerGroup

            self.learner_group = LearnerGroup(
                PPOLearner, lk, backend=cfg.learner_backend,
                mesh=cfg.learner_mesh, num_learners=cfg.num_learners)
            self.learner = None
        else:
            self.learner = PPOLearner(**lk)
        self.workers = [
            RolloutWorker.options(num_cpus=1).remote(
                cfg.env_maker, cfg.num_envs_per_worker, cfg.seed + 1000 * (i + 1),
                cfg.obs_dim, cfg.num_actions)
            for i in range(cfg.num_rollout_workers)
        ]
        self._rng = np.random.default_rng(cfg.seed)
        self._broadcast_weights()
        self._reward_history: List[float] = []
        self._total_steps = 0

    def _broadcast_weights(self) -> None:
        from ray_tpu.rllib.learner import broadcast_weights

        w = (self.learner_group.get_weights() if self.learner_group is not None
             else self.learner.get_weights())
        broadcast_weights(w, self.workers)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        # 1. parallel sampling
        samples = ray_tpu.get([
            wk.sample.remote(cfg.rollout_fragment_length) for wk in self.workers])
        # 2. GAE per worker batch, then concat + flatten [T,N]->[T*N]
        flats: List[Dict[str, np.ndarray]] = []
        episode_returns: List[float] = []
        for batch in samples:
            adv, ret = compute_gae(batch, cfg.gamma, cfg.lambda_)
            T, N = batch["actions"].shape
            flats.append({
                "obs": batch["obs"].reshape(T * N, -1),
                "actions": batch["actions"].reshape(-1),
                "logp": batch["logp"].reshape(-1),
                "advantages": adv.reshape(-1),
                "returns": ret.reshape(-1),
            })
            episode_returns.extend(batch["episode_returns"].tolist())
        flat = {k: np.concatenate([f[k] for f in flats]) for k in flats[0]}
        adv = flat["advantages"]
        flat["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        self._total_steps += int(flat["actions"].size)
        # 3. learner update (group-scaled when configured: reference
        # training_step -> LearnerGroup.update, learner_group.py:52)
        target = self.learner_group if self.learner_group is not None else self.learner
        stats = target.update_minibatches(
            flat, cfg.num_sgd_iter, cfg.sgd_minibatch_size, self._rng)
        # 4. broadcast new weights
        self._broadcast_weights()
        if episode_returns:
            self._reward_history.extend(episode_returns)
            self._reward_history = self._reward_history[-100:]
        mean_reward = float(np.mean(self._reward_history)) if self._reward_history else 0.0
        return {
            "episode_reward_mean": mean_reward,
            "num_env_steps_sampled": self._total_steps,
            **stats,
        }

    def get_weights(self):
        if self.learner_group is not None:
            return self.learner_group.get_weights()
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        if self.learner_group is not None:
            self.learner_group.set_weights(weights)
        else:
            self.learner.set_weights(weights)
        self._broadcast_weights()

    def stop(self) -> None:
        if self.learner_group is not None:
            self.learner_group.shutdown()
        self._kill_workers(self.workers)
