"""Algorithm base: the Tune Trainable contract for RL.

Mirrors the reference's `Algorithm` (rllib/algorithms/algorithm.py:149):
`train()` runs one `training_step` iteration and returns metrics;
save/restore expose checkpoints so Tune schedulers (ASHA/PBT) drive RL
experiments unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint


class Algorithm:
    def __init__(self, config: Dict[str, Any]):
        self.config = dict(config)
        self.iteration = 0
        self.setup(self.config)

    # -- subclass hooks --
    def setup(self, config: Dict[str, Any]) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def get_weights(self) -> Any:
        raise NotImplementedError

    def set_weights(self, weights: Any) -> None:
        raise NotImplementedError

    # -- Trainable contract --
    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        metrics = self.training_step()
        metrics["training_iteration"] = self.iteration
        return metrics

    def save(self) -> Checkpoint:
        return Checkpoint.from_dict({
            "weights": self.get_weights(), "iteration": self.iteration})

    def restore(self, checkpoint: Checkpoint) -> None:
        data = checkpoint.to_dict()
        self.set_weights(data["weights"])
        self.iteration = data.get("iteration", 0)

    def stop(self) -> None:
        pass

    @staticmethod
    def _kill_workers(workers) -> None:
        """Best-effort teardown of a worker-actor fleet: an already-dead or
        unreachable worker is the expected case during shutdown and is
        logged, not raised — but programming errors still propagate."""
        import logging

        import ray_tpu

        for w in workers:
            try:
                ray_tpu.kill(w)
            except (OSError, TimeoutError, ValueError, KeyError,
                    RuntimeError) as e:
                logging.getLogger(__name__).debug(
                    "stop(): worker already gone (%s)", e)
