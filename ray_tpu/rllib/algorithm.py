"""Algorithm base: the Tune Trainable contract for RL.

Mirrors the reference's `Algorithm` (rllib/algorithms/algorithm.py:149):
`train()` runs one `training_step` iteration and returns metrics;
save/restore expose checkpoints so Tune schedulers (ASHA/PBT) drive RL
experiments unchanged.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint


class Algorithm:
    def __init__(self, config: Dict[str, Any]):
        self.config = dict(config)
        self.iteration = 0
        self.setup(self.config)

    # -- subclass hooks --
    def setup(self, config: Dict[str, Any]) -> None:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def get_weights(self) -> Any:
        raise NotImplementedError

    def set_weights(self, weights: Any) -> None:
        raise NotImplementedError

    # -- Trainable contract --
    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        metrics = self.training_step()
        metrics["training_iteration"] = self.iteration
        interval = getattr(getattr(self, "cfg", None),
                           "evaluation_interval", None)
        if interval and self.iteration % interval == 0:
            metrics["evaluation"] = self.evaluate()
        return metrics

    def evaluate(self, num_episodes: Optional[int] = None) -> Dict[str, Any]:
        """Deterministic evaluation episodes spread over the rollout
        workers (reference Algorithm.evaluate, algorithm.py:847; the
        in-place evaluation_num_workers=0 mode — workers run fresh envs,
        training state untouched)."""
        import ray_tpu
        from ray_tpu.rllib.evaluation import summarize_eval

        workers = getattr(self, "workers", None)
        if not workers:
            raise NotImplementedError(
                f"{type(self).__name__} has no rollout workers to evaluate "
                "with; override evaluate()")
        n = num_episodes or getattr(getattr(self, "cfg", None),
                                    "evaluation_duration", 5)
        per = [n // len(workers)] * len(workers)
        for i in range(n % len(workers)):
            per[i] += 1
        refs = [w.eval_episodes.remote(k, seed=1000 + 7 * i)
                for i, (w, k) in enumerate(zip(workers, per)) if k > 0]
        return summarize_eval(ray_tpu.get(refs))

    def save(self) -> Checkpoint:
        return Checkpoint.from_dict({
            "weights": self.get_weights(), "iteration": self.iteration})

    def restore(self, checkpoint: Checkpoint) -> None:
        data = checkpoint.to_dict()
        self.set_weights(data["weights"])
        self.iteration = data.get("iteration", 0)

    def stop(self) -> None:
        pass

    @staticmethod
    def _kill_workers(workers) -> None:
        """Best-effort teardown of a worker-actor fleet: an already-dead or
        unreachable worker is the expected case during shutdown and is
        logged, not raised — but programming errors still propagate."""
        import logging

        import ray_tpu

        for w in workers:
            try:
                ray_tpu.kill(w)
            except (OSError, TimeoutError, ValueError, KeyError,
                    RuntimeError) as e:
                logging.getLogger(__name__).debug(
                    "stop(): worker already gone (%s)", e)
