"""Dreamer: world-model RL with latent imagination (Hafner et al. 2020).

Reference parity: rllib/algorithms/dreamer/ (SURVEY §2.3 algorithm list).
Three jointly-trained pieces, all jitted JAX:

  1. RSSM world model — deterministic GRU path h_t plus stochastic latent
     z_t; prior p(z|h) learns dynamics, posterior q(z|h, obs) filters real
     observations; decoder and reward head reconstruct the environment.
     Loss = reconstruction + reward MSE + KL(q || p) with free nats.
  2. Actor pi(a|h,z) trained purely in IMAGINATION: latent rollouts of
     horizon H from posterior states, maximizing lambda-returns — the
     gradient flows through the learned (differentiable) dynamics, the
     trick that separates Dreamer from model-free RL.
  3. Critic v(h,z) regressed on stopped lambda-returns.

The in-tree env is a continuous point-goal task (obs = [pos, vel, goal],
reward = -|pos - goal|) where the world model is learnable fast enough for
CI; PendulumEnv drops in for a longer run.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.models import init_mlp, mlp_forward


class PointGoalEnv:
    """1-D point mass: accelerate toward a per-episode goal."""

    observation_dim = 3
    action_dim = 1

    def __init__(self, seed: int = 0, episode_len: int = 30):
        self.rng = np.random.default_rng(seed)
        self.episode_len = episode_len

    def reset(self) -> np.ndarray:
        self.pos = float(self.rng.uniform(-1, 1))
        self.vel = 0.0
        self.goal = float(self.rng.uniform(-1, 1))
        self.t = 0
        return self._obs()

    def _obs(self) -> np.ndarray:
        return np.array([self.pos, self.vel, self.goal], np.float32)

    def step(self, action):
        a = float(np.clip(np.asarray(action).ravel()[0], -1, 1))
        self.vel = 0.8 * self.vel + 0.2 * a
        self.pos = float(np.clip(self.pos + 0.3 * self.vel, -2, 2))
        self.t += 1
        reward = -abs(self.pos - self.goal)
        done = self.t >= self.episode_len
        return self._obs(), reward, done, {}


class DreamerConfig:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda s: PointGoalEnv(s)
        self.obs_dim = PointGoalEnv.observation_dim
        self.action_dim = PointGoalEnv.action_dim
        self.deter_dim = 32
        self.stoch_dim = 8
        self.hidden = 64
        self.seq_len = 15
        self.batch_size = 32
        self.horizon = 10
        self.gamma = 0.95
        self.lambda_ = 0.95
        self.free_nats = 0.5
        self.kl_scale = 1.0
        self.model_lr = 1e-3
        self.actor_lr = 1e-4
        self.critic_lr = 3e-4
        self.expl_noise = 0.3
        self.episodes_per_iter = 5
        self.updates_per_iter = 40
        self.buffer_episodes = 500
        self.warmup_episodes = 10
        self.seed = 0

    def training(self, **kw) -> "DreamerConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown option {k!r}")
            if v is not None:
                setattr(self, k, v)
        return self

    def build(self) -> "Dreamer":
        return Dreamer({"dreamer_config": self})


def _init_dense(rng, shape, scale=None):
    scale = scale or np.sqrt(2.0 / shape[0])
    return (rng.standard_normal(shape) * scale).astype(np.float32)


class Dreamer(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        cfg: DreamerConfig = config.get("dreamer_config") or DreamerConfig()
        self.cfg = cfg
        self.env = cfg.env_maker(cfg.seed)
        rng = np.random.default_rng(cfg.seed)
        D, S, H, A = cfg.deter_dim, cfg.stoch_dim, cfg.hidden, cfg.action_dim
        O = cfg.obs_dim
        feat = D + S

        wm = {
            # pre-GRU embed of [z, a] (the paper's dense layer before the
            # recurrent cell) and the GRU cell itself
            "embed": init_mlp(rng, (S + A, H)),
            "gru_xz": _init_dense(rng, (H, 3 * D)),
            "gru_h": _init_dense(rng, (D, 3 * D)),
            "gru_b": np.zeros(3 * D, np.float32),
            # prior p(z|h): h -> 2S
            "prior": init_mlp(rng, (D, H, 2 * S)),
            # posterior q(z|h, obs_embed): obs encoder + head
            "obs_enc": init_mlp(rng, (O, H)),
            "post": init_mlp(rng, (D + H, H, 2 * S)),
            # decoder [h,z] -> obs ; reward head [h,z] -> 1
            "dec": init_mlp(rng, (feat, H, O)),
            "rew": init_mlp(rng, (feat, H, 1)),
        }
        actor = init_mlp(rng, (feat, H, H, A), final_scale=0.01)
        critic = init_mlp(rng, (feat, H, H, 1), final_scale=0.01)
        self.params = {"wm": wm, "actor": actor, "critic": critic}
        # clip 100 as in the paper — the first KL gradients are enormous
        self.opt_model = optax.chain(
            optax.clip_by_global_norm(100.0), optax.adam(cfg.model_lr))
        self.opt_actor = optax.chain(
            optax.clip_by_global_norm(100.0), optax.adam(cfg.actor_lr))
        self.opt_critic = optax.chain(
            optax.clip_by_global_norm(100.0), optax.adam(cfg.critic_lr))
        self.os_model = self.opt_model.init(wm)
        self.os_actor = self.opt_actor.init(actor)
        self.os_critic = self.opt_critic.init(critic)
        self.rng = rng
        self.episodes: List[Dict[str, np.ndarray]] = []
        self._total_steps = 0
        self._reward_history: List[float] = []
        self._jax_key = jax.random.PRNGKey(cfg.seed)

        def gru(wm_p, h, zA):
            zA = jnp.tanh(mlp_forward(wm_p["embed"], zA, 1))
            x_parts = jnp.split(zA @ wm_p["gru_xz"] + wm_p["gru_b"], 3, -1)
            h_parts = jnp.split(h @ wm_p["gru_h"], 3, -1)
            r = jax.nn.sigmoid(x_parts[0] + h_parts[0])
            u = jax.nn.sigmoid(x_parts[1] + h_parts[1])
            cand = jnp.tanh(x_parts[2] + r * h_parts[2])
            return u * cand + (1 - u) * h

        def gaussian(stats):
            mean, std = jnp.split(stats, 2, axis=-1)
            return mean, jax.nn.softplus(std) + 0.1

        def sample(key, mean, std):
            return mean + std * jax.random.normal(key, mean.shape)

        def obs_step(wm_p, key, h, z, a, obs):
            """One filtering step: advance deter state, compute prior and
            posterior, sample posterior z."""
            h = gru(wm_p, h, jnp.concatenate([z, a], -1))
            prior_stats = mlp_forward(wm_p["prior"], h, 2)
            emb = jnp.tanh(mlp_forward(wm_p["obs_enc"], obs, 1))
            post_stats = mlp_forward(
                wm_p["post"], jnp.concatenate([h, emb], -1), 2)
            pm, ps = gaussian(post_stats)
            z_new = sample(key, pm, ps)
            return h, z_new, gaussian(prior_stats), (pm, ps)

        def kl(q, p):
            qm, qs = q
            pm, ps = p
            return (jnp.log(ps / qs) + (qs ** 2 + (qm - pm) ** 2)
                    / (2 * ps ** 2) - 0.5).sum(-1)

        def kl_balanced(post, prior, alpha=0.8):
            """DreamerV2 KL balancing: push the PRIOR toward the posterior
            (weight alpha, posterior stopped) much harder than the posterior
            toward the prior — without this the prior never learns the
            dynamics and imagination is action-blind."""
            sg = jax.lax.stop_gradient
            lhs = kl((sg(post[0]), sg(post[1])), prior)
            rhs = kl(post, (sg(prior[0]), sg(prior[1])))
            return alpha * lhs + (1 - alpha) * rhs

        gamma, lam, horizon = cfg.gamma, cfg.lambda_, cfg.horizon
        free_nats, kl_scale = cfg.free_nats, cfg.kl_scale

        def model_loss(wm_p, key, batch):
            """batch: obs [B,T,O], actions [B,T,A], rewards [B,T]."""
            B, T, _ = batch["obs"].shape
            keys = jax.random.split(key, T)

            def scan_fn(carry, t):
                h, z, loss_kl = carry
                h, z, prior, post = obs_step(
                    wm_p, keys[t], h, z, batch["actions"][:, t],
                    batch["obs"][:, t])
                loss_kl = loss_kl + jnp.maximum(
                    kl_balanced(post, prior), free_nats).mean()
                return (h, z, loss_kl), (h, z)

            h0 = jnp.zeros((B, D))
            z0 = jnp.zeros((B, S))
            (h, z, loss_kl), (hs, zs) = jax.lax.scan(
                scan_fn, (h0, z0, 0.0), jnp.arange(T))
            feats = jnp.concatenate(
                [hs.transpose(1, 0, 2), zs.transpose(1, 0, 2)], -1)  # [B,T,F]
            recon = mlp_forward(wm_p["dec"], feats, 2)
            rew = mlp_forward(wm_p["rew"], feats, 2)[..., 0]
            loss_recon = ((recon - batch["obs"]) ** 2).sum(-1).mean()
            loss_rew = ((rew - batch["rewards"]) ** 2).mean()
            total = loss_recon + loss_rew + kl_scale * loss_kl / T
            aux = {"recon": loss_recon, "reward_mse": loss_rew,
                   "kl": loss_kl / T,
                   "feats": jax.lax.stop_gradient(
                       feats.reshape(B * T, feat))}
            return total, aux

        def policy(actor_p, f):
            return jnp.tanh(mlp_forward(actor_p, f, 3))

        def imagine(wm_p, actor_p, key, start_feats):
            """Roll latent dynamics H steps under the actor; returns
            feats [H+1, N, F] and predicted rewards [H+1, N]."""
            N = start_feats.shape[0]
            h = start_feats[:, :D]
            z = start_feats[:, D:]
            keys = jax.random.split(key, horizon)

            def step(carry, k):
                h, z = carry
                f = jnp.concatenate([h, z], -1)
                a = policy(actor_p, f)
                h2 = gru(wm_p, h, jnp.concatenate([z, a], -1))
                pm, ps = gaussian(mlp_forward(wm_p["prior"], h2, 2))
                z2 = sample(k, pm, ps)
                return (h2, z2), jnp.concatenate([h2, z2], -1)

            (_, _), feats = jax.lax.scan(step, (h, z), keys)
            feats = jnp.concatenate([start_feats[None], feats], 0)
            rewards = mlp_forward(wm_p["rew"], feats, 2)[..., 0]
            return feats, rewards

        def lambda_returns(rewards, values):
            """TD(lambda) over the imagined horizon ([H+1, N] arrays)."""
            Hn = rewards.shape[0] - 1

            def step(nxt, t):
                ret = rewards[t + 1] + gamma * (
                    (1 - lam) * values[t + 1] + lam * nxt)
                return ret, ret

            _, rets = jax.lax.scan(
                step, values[-1], jnp.arange(Hn - 1, -1, -1))
            return rets[::-1]  # [H, N] aligned with feats[0..H-1]

        def actor_loss(actor_p, wm_p, critic_p, key, start_feats):
            feats, rewards = imagine(wm_p, actor_p, key, start_feats)
            values = mlp_forward(critic_p, feats, 3)[..., 0]
            rets = lambda_returns(rewards, values)
            return -rets.mean()

        def critic_loss(critic_p, targets_feats, targets):
            v = mlp_forward(critic_p, targets_feats, 3)[..., 0]
            return ((v - targets) ** 2).mean()

        def update(params, opts, key, batch):
            wm_p, actor_p, critic_p = (
                params["wm"], params["actor"], params["critic"])
            k1, k2, k3 = jax.random.split(key, 3)
            (mloss, aux), mgrads = jax.value_and_grad(
                model_loss, has_aux=True)(wm_p, k1, batch)
            mupd, os_m = self.opt_model.update(mgrads, opts[0], wm_p)
            wm_p = optax.apply_updates(wm_p, mupd)

            start = aux["feats"]
            aloss, agrads = jax.value_and_grad(actor_loss)(
                actor_p, wm_p, critic_p, k2, start)
            aupd, os_a = self.opt_actor.update(agrads, opts[1], actor_p)
            actor_p = optax.apply_updates(actor_p, aupd)

            feats, rewards = imagine(wm_p, actor_p, k3, start)
            values = mlp_forward(critic_p, feats, 3)[..., 0]
            rets = jax.lax.stop_gradient(lambda_returns(rewards, values))
            tfeats = jax.lax.stop_gradient(feats[:-1].reshape(-1, feat))
            closs, cgrads = jax.value_and_grad(critic_loss)(
                critic_p, tfeats, rets.reshape(-1))
            cupd, os_c = self.opt_critic.update(cgrads, opts[2], critic_p)
            critic_p = optax.apply_updates(critic_p, cupd)

            new_params = {"wm": wm_p, "actor": actor_p, "critic": critic_p}
            stats = {"model_loss": mloss, "actor_loss": aloss,
                     "critic_loss": closs, "recon": aux["recon"],
                     "reward_mse": aux["reward_mse"], "kl": aux["kl"]}
            return new_params, (os_m, os_a, os_c), stats

        self._update = jax.jit(update)

        def filter_step(wm_p, key, h, z, a, obs):
            h, z, _, _ = obs_step(wm_p, key, h, z, a, obs)
            return h, z

        self._filter_step = jax.jit(filter_step)
        self._policy = jax.jit(policy)
        self._feat_dim = feat
        self._dims = (D, S, A)

    # ------------------------------------------------------------- acting
    def _act(self, h, z, obs, noise: float):
        import jax
        import jax.numpy as jnp

        D, S, A = self._dims
        self._jax_key, k = jax.random.split(self._jax_key)
        f = np.concatenate([np.asarray(h)[0], np.asarray(z)[0]])
        a = np.asarray(self._policy(self.params["actor"], f[None]))[0]
        if noise > 0:
            a = np.clip(a + noise * self.rng.standard_normal(A), -1, 1)
        return a

    def _run_episode(self, noise: float, store: bool = True) -> float:
        import jax.numpy as jnp

        D, S, A = self._dims
        env = self.env
        obs = env.reset()
        h = jnp.zeros((1, D))
        z = jnp.zeros((1, S))
        a = np.zeros(A, np.float32)
        traj = {"obs": [], "actions": [], "rewards": []}
        total = 0.0
        import jax

        while True:
            # filter the real observation into the latent state
            self._jax_key, k = jax.random.split(self._jax_key)
            h, z = self._filter_step(
                self.params["wm"], k, h, z,
                jnp.asarray(a, jnp.float32)[None], jnp.asarray(obs)[None])
            a = self._act(h, z, obs, noise)
            nxt, reward, done, _ = env.step(a)
            traj["obs"].append(obs)
            traj["actions"].append(a)
            traj["rewards"].append(reward)
            total += reward
            self._total_steps += 1 if store else 0
            obs = nxt
            if done:
                break
        if store:
            self.episodes.append({
                "obs": np.asarray(traj["obs"], np.float32),
                "actions": np.asarray(traj["actions"], np.float32),
                "rewards": np.asarray(traj["rewards"], np.float32),
            })
            self.episodes = self.episodes[-self.cfg.buffer_episodes:]
        return total

    def _sample_batch(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        cfg = self.cfg
        B, L = cfg.batch_size, cfg.seq_len
        obs = np.zeros((B, L, cfg.obs_dim), np.float32)
        act = np.zeros((B, L, cfg.action_dim), np.float32)
        rew = np.zeros((B, L), np.float32)
        for b in range(B):
            ep = self.episodes[self.rng.integers(len(self.episodes))]
            T = len(ep["rewards"])
            # align with the filtering recurrence: at index t the model
            # consumes (a_{t-1}, obs_t) and the reward head predicts the
            # reward received on ARRIVING at obs_t (= rewards[t-1])
            prev_a = np.concatenate(
                [np.zeros((1, cfg.action_dim), np.float32),
                 ep["actions"][:-1]])
            arr_r = np.concatenate([[0.0], ep["rewards"][:-1]]).astype(
                np.float32)
            if T <= L:
                obs[b, :T] = ep["obs"]
                act[b, :T] = prev_a
                rew[b, :T] = arr_r
            else:
                s = self.rng.integers(0, T - L + 1)
                obs[b] = ep["obs"][s:s + L]
                act[b] = prev_a[s:s + L]
                rew[b] = arr_r[s:s + L]
        return {"obs": jnp.asarray(obs), "actions": jnp.asarray(act),
                "rewards": jnp.asarray(rew)}

    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.cfg
        returns = [self._run_episode(cfg.expl_noise)
                   for _ in range(cfg.episodes_per_iter)]
        stats: Dict[str, Any] = {}
        if len(self.episodes) >= cfg.warmup_episodes:
            opts = (self.os_model, self.os_actor, self.os_critic)
            for _ in range(cfg.updates_per_iter):
                self._jax_key, k = jax.random.split(self._jax_key)
                self.params, opts, stats = self._update(
                    self.params, opts, k, self._sample_batch())
            self.os_model, self.os_actor, self.os_critic = opts
            stats = {k2: float(v) for k2, v in jax.device_get(stats).items()}
        self._reward_history.extend(returns)
        self._reward_history = self._reward_history[-50:]
        return {"episode_reward_mean": float(np.mean(self._reward_history)),
                "num_env_steps_sampled": self._total_steps, **stats}

    def greedy_return(self, episodes: int = 10) -> float:
        return float(np.mean([self._run_episode(0.0, store=False)
                              for _ in range(episodes)]))

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, jax.device_get(self.params))

    def set_weights(self, weights) -> None:
        self.params = weights
