"""DT: Decision Transformer — offline RL as return-conditioned sequence
modeling (Chen et al. 2021).

Reference parity: rllib/algorithms/dt/ (SURVEY §2.3's algorithm list). The
reference wraps a torch GPT; here the model is a small causal transformer
written directly in JAX, jitted end to end — interleaved
(return-to-go, state, action) tokens, action predicted from each state
token's output. Training samples fixed-K windows from logged episodes;
evaluation rolls the policy autoregressively conditioned on a target
return.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import CartPoleEnv


# ------------------------------------------------------------------ model


def _init_dt_params(seed: int, obs_dim: int, num_actions: int, d: int,
                    n_layers: int, max_ep_len: int) -> Dict[str, Any]:
    rng = np.random.default_rng(seed)

    def dense(shape, scale=0.02):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    p: Dict[str, Any] = {
        "rtg_w": dense((1, d)), "rtg_b": np.zeros(d, np.float32),
        "obs_w": dense((obs_dim, d)), "obs_b": np.zeros(d, np.float32),
        "act_emb": dense((num_actions + 1, d)),  # last row: "no action" pad
        "time_emb": dense((max_ep_len + 1, d)),
        "head_w": dense((d, num_actions)),
        "head_b": np.zeros(num_actions, np.float32),
        "lnf_s": np.ones(d, np.float32), "lnf_b": np.zeros(d, np.float32),
    }
    for i in range(n_layers):
        p[f"l{i}"] = {
            "ln1_s": np.ones(d, np.float32), "ln1_b": np.zeros(d, np.float32),
            "qkv_w": dense((d, 3 * d)), "qkv_b": np.zeros(3 * d, np.float32),
            "proj_w": dense((d, d)), "proj_b": np.zeros(d, np.float32),
            "ln2_s": np.ones(d, np.float32), "ln2_b": np.zeros(d, np.float32),
            "fc1_w": dense((d, 4 * d)), "fc1_b": np.zeros(4 * d, np.float32),
            "fc2_w": dense((4 * d, d)), "fc2_b": np.zeros(d, np.float32),
        }
    return p


def _dt_forward(params, rtg, obs, actions, timesteps, pad_mask,
                n_layers: int, n_heads: int):
    """rtg [B,K,1], obs [B,K,D], actions [B,K] (num_actions = pad),
    timesteps [B,K], pad_mask [B,K] (1=real). Returns action logits [B,K,A]
    predicted at each state token."""
    import jax
    import jax.numpy as jnp

    def ln(x, s, b):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * s + b

    B, K = actions.shape
    d = params["obs_w"].shape[1]
    te = params["time_emb"][timesteps]  # [B,K,d]
    tok_r = rtg @ params["rtg_w"] + params["rtg_b"] + te
    tok_s = obs @ params["obs_w"] + params["obs_b"] + te
    tok_a = params["act_emb"][actions] + te
    # interleave -> [B, 3K, d] in order (R_t, s_t, a_t)
    x = jnp.stack([tok_r, tok_s, tok_a], axis=2).reshape(B, 3 * K, d)

    T = 3 * K
    causal = jnp.tril(jnp.ones((T, T), bool))
    keep = jnp.repeat(pad_mask, 3, axis=1).astype(bool)  # [B,T]
    mask = causal[None] & keep[:, None, :]  # [B,T,T]

    hd = d // n_heads
    for i in range(n_layers):
        lp = params[f"l{i}"]
        h = ln(x, lp["ln1_s"], lp["ln1_b"])
        qkv = h @ lp["qkv_w"] + lp["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, n_heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(B, T, n_heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(B, T, n_heads, hd).transpose(0, 2, 1, 3)
        att = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(hd)
        att = jnp.where(mask[:, None], att, -1e9)
        att = jax.nn.softmax(att, axis=-1)
        out = (att @ v).transpose(0, 2, 1, 3).reshape(B, T, d)
        x = x + out @ lp["proj_w"] + lp["proj_b"]
        h = ln(x, lp["ln2_s"], lp["ln2_b"])
        h = jax.nn.gelu(h @ lp["fc1_w"] + lp["fc1_b"])
        x = x + h @ lp["fc2_w"] + lp["fc2_b"]

    x = ln(x, params["lnf_s"], params["lnf_b"])
    state_out = x.reshape(B, K, 3, d)[:, :, 1]  # output above each s_t
    return state_out @ params["head_w"] + params["head_b"]


# ----------------------------------------------------------------- dataset


def _split_episodes(dataset: Dict[str, np.ndarray]) -> List[Dict[str, np.ndarray]]:
    """Columnar transitions (offline.collect_episodes format) -> episode
    list with per-step return-to-go."""
    n = len(dataset["dones"])
    bounds = (np.flatnonzero(dataset["dones"] > 0.5) + 1).tolist()
    if not bounds or bounds[-1] != n:  # trailing truncated episode
        bounds.append(n)
    episodes, start = [], 0
    for end in bounds:
        sl = slice(start, end)
        rew = dataset["rewards"][sl]
        episodes.append({
            "obs": dataset["obs"][sl],
            "actions": dataset["actions"][sl],
            "rtg": np.cumsum(rew[::-1])[::-1].astype(np.float32),
        })
        start = end
    return episodes


# --------------------------------------------------------------- algorithm


class DTConfig:
    def __init__(self):
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.context_len = 20
        self.embed_dim = 64
        self.n_layers = 2
        self.n_heads = 2
        self.max_ep_len = 500
        self.return_scale = 100.0
        self.lr = 1e-3
        self.batch_size = 64
        self.updates_per_iter = 50
        self.target_return = 150.0
        self.seed = 0
        self.dataset: Optional[Dict[str, np.ndarray]] = None

    def environment(self, *, obs_dim=None, num_actions=None) -> "DTConfig":
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def offline_data(self, dataset: Dict[str, np.ndarray]) -> "DTConfig":
        self.dataset = dataset
        return self

    def training(self, *, lr=None, batch_size=None, context_len=None,
                 updates_per_iter=None, embed_dim=None, n_layers=None,
                 target_return=None, return_scale=None,
                 seed=None) -> "DTConfig":
        for k, v in [("lr", lr), ("batch_size", batch_size),
                     ("context_len", context_len),
                     ("updates_per_iter", updates_per_iter),
                     ("embed_dim", embed_dim), ("n_layers", n_layers),
                     ("target_return", target_return),
                     ("return_scale", return_scale), ("seed", seed)]:
            if v is not None:
                setattr(self, k, v)
        return self

    def build(self) -> "DT":
        return DT({"dt_config": self})


class DT(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import optax

        cfg: DTConfig = config.get("dt_config") or DTConfig()
        if cfg.dataset is None:
            raise ValueError("DTConfig.offline_data(dataset) is required")
        self.cfg = cfg
        self.episodes = _split_episodes(cfg.dataset)
        self._ep_lens = np.array([len(e["actions"]) for e in self.episodes])
        self.params = _init_dt_params(
            cfg.seed, cfg.obs_dim, cfg.num_actions, cfg.embed_dim,
            cfg.n_layers, cfg.max_ep_len)
        self.optimizer = optax.adamw(cfg.lr, weight_decay=1e-4)
        self.opt_state = self.optimizer.init(self.params)
        self.rng = np.random.default_rng(cfg.seed)

        n_layers, n_heads = cfg.n_layers, cfg.n_heads

        def loss_fn(params, batch):
            import jax.numpy as jnp

            logits = _dt_forward(
                params, batch["rtg"], batch["obs"], batch["actions_in"],
                batch["timesteps"], batch["mask"], n_layers, n_heads)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(
                logp, batch["actions"][..., None], axis=-1)[..., 0]
            m = batch["mask"]
            return (nll * m).sum() / jnp.maximum(m.sum(), 1.0)

        def update(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            import optax as _optax

            return _optax.apply_updates(params, updates), opt_state, loss

        self._update = jax.jit(update)
        self._forward = jax.jit(
            lambda p, r, o, a, t, m: _dt_forward(
                p, r, o, a, t, m, n_layers, n_heads))

    def _sample_batch(self) -> Dict[str, np.ndarray]:
        cfg = self.cfg
        B, K, D = cfg.batch_size, cfg.context_len, cfg.obs_dim
        probs = self._ep_lens / self._ep_lens.sum()
        batch = {
            "rtg": np.zeros((B, K, 1), np.float32),
            "obs": np.zeros((B, K, D), np.float32),
            "actions": np.zeros((B, K), np.int32),
            "actions_in": np.full((B, K), cfg.num_actions, np.int32),
            "timesteps": np.zeros((B, K), np.int32),
            "mask": np.zeros((B, K), np.float32),
        }
        for b in range(B):
            ep = self.episodes[self.rng.choice(len(self.episodes), p=probs)]
            L = len(ep["actions"])
            end = self.rng.integers(1, L + 1)  # exclusive
            start = max(0, end - K)
            n = end - start
            batch["rtg"][b, K - n:, 0] = ep["rtg"][start:end] / cfg.return_scale
            batch["obs"][b, K - n:] = ep["obs"][start:end]
            batch["actions"][b, K - n:] = ep["actions"][start:end]
            batch["actions_in"][b, K - n:] = ep["actions"][start:end]
            batch["timesteps"][b, K - n:] = np.arange(start, end).clip(
                0, cfg.max_ep_len)
            batch["mask"][b, K - n:] = 1.0
        return batch

    def training_step(self) -> Dict[str, Any]:
        losses = []
        for _ in range(self.cfg.updates_per_iter):
            batch = self._sample_batch()
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state, batch)
            losses.append(float(loss))
        return {"loss": float(np.mean(losses)),
                "num_updates": self.iteration * self.cfg.updates_per_iter}

    # ------------------------------------------------------------- rollout
    def compute_action(self, history: Dict[str, List], obs: np.ndarray,
                       rtg: float) -> int:
        """Greedy action from the trailing context window."""
        cfg = self.cfg
        K = cfg.context_len
        hist_obs = (history["obs"] + [obs])[-K:]
        hist_rtg = (history["rtg"] + [rtg])[-K:]
        hist_act = history["actions"][-(K - 1):] if K > 1 else []
        n = len(hist_obs)
        rtg_in = np.zeros((1, K, 1), np.float32)
        obs_in = np.zeros((1, K, cfg.obs_dim), np.float32)
        act_in = np.full((1, K), cfg.num_actions, np.int32)
        ts = np.zeros((1, K), np.int32)
        mask = np.zeros((1, K), np.float32)
        rtg_in[0, K - n:, 0] = np.asarray(hist_rtg) / cfg.return_scale
        obs_in[0, K - n:] = np.asarray(hist_obs)
        if hist_act:
            act_in[0, K - len(hist_act) - 1:K - 1] = hist_act
        t0 = len(history["obs"]) - n + 1
        ts[0, K - n:] = (np.arange(t0, t0 + n)).clip(0, cfg.max_ep_len)
        mask[0, K - n:] = 1.0
        logits = self._forward(self.params, rtg_in, obs_in, act_in, ts, mask)
        return int(np.argmax(np.asarray(logits)[0, -1]))

    def evaluate(self, env_maker: Callable[[int], Any],
                 num_episodes: int = 5,
                 target_return: Optional[float] = None,
                 max_steps: int = 500, seed: int = 10_000) -> float:
        """Mean achieved return rolling out conditioned on target_return."""
        target = (target_return if target_return is not None
                  else self.cfg.target_return)
        totals = []
        for ep in range(num_episodes):
            env = env_maker(seed + ep)
            obs = env.reset()
            history = {"obs": [], "rtg": [], "actions": []}
            rtg, total = float(target), 0.0
            for _ in range(max_steps):
                a = self.compute_action(history, np.asarray(obs), rtg)
                history["obs"].append(np.asarray(obs))
                history["rtg"].append(rtg)
                history["actions"].append(a)
                obs, r, done, _ = env.step(a)
                total += r
                rtg = max(rtg - r, 1.0)
                if done:
                    break
            totals.append(total)
        return float(np.mean(totals))

    def get_weights(self):
        import jax

        return jax.device_get(self.params)

    def set_weights(self, weights) -> None:
        self.params = weights
        self.opt_state = self.optimizer.init(self.params)
