"""RL fleet: serve-deployed rollout replicas feeding a checkpointed learner.

The composite scenario the serve+train stack exists for (ROADMAP item 2,
PAPERS.md Podracer/RLAX fleets): N rollout replicas behind a serve
deployment generate episodes — riding the continuous-batching decode engine
when the policy is a transformer, plain env rollouts otherwise — ship
sample batches to a learner actor through the zero-copy object plane, and
receive updated weights back through the serve *lightweight-update* path
(`serve.reconfigure`: in-place user_config push, no rolling restart).

Robustness contract:

- **Weight epochs.** Every broadcast carries a monotonically increasing
  ``epoch``. Replicas fence regressions in ``reconfigure()`` (a rolling
  update replaying an old config cannot roll weights back) and every
  rollout envelope records the epoch it was generated under; the learner
  drops samples older than ``max_staleness`` epochs and histograms the lag.
- **Exactly-once sample accounting.** The learner dedupes rollout ids.
  The applied-id set rides the checkpoint, so a crash-restart resumes from
  the latest *complete* checkpoint (`train.checkpointing.latest_checkpoint`)
  without double-applying any batch that checkpoint already contains —
  post-checkpoint batches were rolled back with the params, so re-applying
  them is correct, not a duplicate.
- **Partition tolerance.** The two loop boundaries are named fault points
  (`fleet_ingest`: replicas->learner, `fleet_weights`: learner->replicas)
  judged by the injector's partition rules, so a
  ``partition:learner|replicas`` blackhole starves the loop without killing
  it; the driver retries with backoff until heal — no hung futures, every
  future resolves or times out.

`python -m ray_tpu.rllib.trainstorm` composes all three failure modes over
this module and commits the TRAINSTORM artifact.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.rpc import (RpcDisconnected, fault_point,
                              get_fault_injector)
from ray_tpu.util import tracing

logger = logging.getLogger(__name__)

# Named fault-point labels for the loop's two logical boundaries, plus the
# literal group labels `partition:learner|replicas` specs resolve against.
INGEST_FAULT_POINT = "fleet_ingest"      # sample handoff into the learner
WEIGHTS_FAULT_POINT = "fleet_weights"    # weight broadcast to replicas
LEARNER_GROUP = "learner"
REPLICA_GROUP = "replicas"
LEARNER_ACTOR_NAME = "fleet_learner"


def define_fleet_groups(inj=None):
    """Register the `learner` / `replicas` partition groups (each a single
    literal label — these are logical planes, not node addresses) on the
    installed injector so `partition:learner|replicas` severs exactly the
    fleet_ingest / fleet_weights boundaries. No-op without an injector."""
    inj = inj if inj is not None else get_fault_injector()
    if inj is None:
        return None
    inj.define_group(LEARNER_GROUP, {LEARNER_GROUP})
    inj.define_group(REPLICA_GROUP, {REPLICA_GROUP})
    return inj


# --------------------------------------------------------------------- config


@dataclasses.dataclass
class FleetConfig:
    """Knobs for the rollout->learner loop. Every field can be overridden
    with a ``RAY_TPU_FLEET_<FIELD>`` environment variable (same pattern as
    ServeConfig) so chaos harnesses and CI shrink the fleet without code."""

    num_replicas: int = 2
    num_envs: int = 2            # vector envs per replica (mlp policy)
    rollout_len: int = 32        # steps per env per sample() call
    max_staleness: int = 2       # drop samples > this many epochs old
    checkpoint_every: int = 4    # learner steps between checkpoints
    keep_checkpoints: int = 3    # retention for gc_checkpoints
    broadcast_every: int = 1     # learner steps between weight broadcasts
    lr: float = 3e-4
    gamma: float = 0.99
    lam: float = 0.95
    sgd_epochs: int = 2
    minibatch_size: int = 64
    seed: int = 0
    policy: str = "mlp"          # "mlp" (env rollouts) | "transformer"
    max_new_tokens: int = 8      # transformer policy: decode length
    ingest_timeout_s: float = 30.0     # single learner-call timeout
    ingest_backoff_s: float = 0.2      # retry backoff while partitioned
    ingest_deadline_s: float = 60.0    # give up (drop batch) after this
    sample_timeout_s: float = 60.0
    deployment_name: str = "rollout_fleet"

    @classmethod
    def from_env(cls, **overrides) -> "FleetConfig":
        kwargs: Dict[str, Any] = {}
        for f in dataclasses.fields(cls):
            raw = os.environ.get(f"RAY_TPU_FLEET_{f.name.upper()}")
            if raw is None:
                continue
            if f.type in ("int", int):
                kwargs[f.name] = int(raw)
            elif f.type in ("float", float):
                kwargs[f.name] = float(raw)
            else:
                kwargs[f.name] = raw
        kwargs.update(overrides)
        return cls(**kwargs)


# ----------------------------------------------------------- rollout replicas


class _MlpRollouts:
    """Plain env-rollout policy: the PPO RolloutWorkerImpl over CartPole."""

    def __init__(self, cfg: FleetConfig, seed: int):
        from ray_tpu.rllib.env import CartPoleEnv
        from ray_tpu.rllib.ppo import RolloutWorkerImpl

        self._worker = RolloutWorkerImpl(
            CartPoleEnv, num_envs=cfg.num_envs, seed=seed,
            obs_dim=4, num_actions=2)

    def set_weights(self, weights) -> None:
        self._worker.set_weights(weights)

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        return self._worker.sample(num_steps)


class _TransformerRollouts:
    """Transformer policy: episodes are sampled continuations out of the
    continuous-batching decode engine (PR 16) — the Podracer shape where
    the 'environment step' IS a model decode. The sample batch ships token
    sequences; the learner applies a next-token LM step on them."""

    def __init__(self, cfg: FleetConfig, seed: int):
        import jax

        from ray_tpu.models import ModelConfig, init_params

        self._mcfg = ModelConfig.tiny()
        self._cfg = cfg
        self._params = init_params(jax.random.PRNGKey(seed), self._mcfg)
        self._rng = np.random.default_rng(seed)
        self._engine = None
        self._rebuild_engine()

    def _rebuild_engine(self) -> None:
        from ray_tpu.models.serving import ContinuousBatchingEngine

        old, self._engine = self._engine, None
        if old is not None:
            old.stop_driver()
        self._engine = ContinuousBatchingEngine(
            self._params, self._mcfg, num_slots=2, max_len=64)
        self._engine.start_driver()

    def set_weights(self, weights) -> None:
        import jax.numpy as jnp
        import jax

        self._params = jax.tree_util.tree_map(jnp.asarray, weights)
        # the engine closed over the old params; swap in a fresh one
        self._rebuild_engine()

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        n_seqs = max(1, num_steps // self._cfg.max_new_tokens)
        prompt_len = 4
        seqs = []
        for _ in range(n_seqs):
            prompt = [int(t) for t in self._rng.integers(
                1, self._mcfg.vocab_size, size=prompt_len)]
            toks = self._engine.generate(
                prompt, max_new_tokens=self._cfg.max_new_tokens)
            seqs.append(prompt + list(toks))
        width = max(len(s) for s in seqs)
        tokens = np.zeros((len(seqs), width), np.int32)
        for i, s in enumerate(seqs):
            tokens[i, :len(s)] = s
        return {"tokens": tokens,
                "episode_returns": np.array(
                    [float(len(s) - prompt_len) for s in seqs], np.float32)}


def _make_policy(cfg: FleetConfig, seed: int):
    if cfg.policy == "transformer":
        return _TransformerRollouts(cfg, seed)
    return _MlpRollouts(cfg, seed)


def rollout_deployment(cfg: FleetConfig):
    """Build the serve deployment class for the rollout fleet.

    Weight delivery is `reconfigure(user_config)` — the serve lightweight-
    update path — with **epoch fencing**: a config whose epoch is <= the
    replica's current epoch is refused *silently* (counted, not raised).
    Raising would trip the controller's rolling-redeploy fallback and
    restart the whole fleet over what is by definition a no-op."""

    @serve.deployment(name=cfg.deployment_name, num_replicas=cfg.num_replicas)
    class RolloutReplica:
        def __init__(self, cfg_dict: dict):
            self._cfg = FleetConfig(**cfg_dict)
            # replicas must not generate identical trajectories: decorrelate
            # the env/rng seed by pid while keeping the run seeded overall
            self._impl = _make_policy(
                self._cfg, self._cfg.seed + (os.getpid() % 10000))
            self._epoch = -1          # no weights applied yet
            self._fenced = 0
            self._applied_updates = 0
            self._lock = threading.Lock()

        def reconfigure(self, user_config) -> dict:
            if not isinstance(user_config, dict) or "epoch" not in user_config:
                return {"applied": False, "reason": "not-a-weight-config"}
            epoch = int(user_config["epoch"])
            with self._lock:
                if epoch <= self._epoch:
                    # FENCE: out-of-order broadcast (rolling update replaying
                    # an older config, or a delayed push landing late).
                    self._fenced += 1
                    logger.info("replica fenced weight epoch %d (at %d)",
                                epoch, self._epoch)
                    return {"applied": False, "reason": "fenced",
                            "epoch": self._epoch}
                self._impl.set_weights(user_config["weights"])
                self._epoch = epoch
                self._applied_updates += 1
                return {"applied": True, "epoch": epoch}

        def sample(self, num_steps: Optional[int] = None) -> dict:
            """One rollout. Returns a small envelope; the batch itself goes
            through the zero-copy object plane (`ray_tpu.put` here, shm view
            on the learner's same-node `get`) instead of riding the serve
            response path. A replica killed mid-call is retried on a peer by
            the handle's mid-request failover; the fresh uuid per attempt
            keeps retries dedupe-transparent at the learner."""
            with self._lock:
                if self._epoch < 0:
                    return {"rollout_id": None, "weight_epoch": -1,
                            "ref": None, "reason": "no-weights-yet"}
                n = int(num_steps or self._cfg.rollout_len)
                batch = self._impl.sample(n)
            return {"rollout_id": uuid.uuid4().hex,
                    "weight_epoch": self._epoch,
                    "ref": ray_tpu.put(batch),
                    "num_env_steps": n * self._cfg.num_envs,
                    "pid": os.getpid()}

        def fence_stats(self) -> dict:
            with self._lock:
                return {"epoch": self._epoch, "fenced": self._fenced,
                        "applied_updates": self._applied_updates,
                        "pid": os.getpid()}

    return RolloutReplica


# ---------------------------------------------------------------- the learner


class FleetLearnerImpl:
    """Checkpointed learner with exactly-once ingest accounting.

    State = params/opt pytrees + (step, epoch, applied rollout ids), saved
    atomically every `checkpoint_every` steps via train.checkpointing.
    `ingest` is the only mutation path: dedupe -> staleness gate -> update.
    """

    def __init__(self, cfg_dict: dict, ckpt_root: str, min_epoch: int = 0):
        self._cfg = cfg = FleetConfig(**cfg_dict)
        self._ckpt_root = ckpt_root
        self._core = self._build_core(cfg)
        self._step = 0
        self._epoch = 0
        self._applied_ids: set = set()
        self._staleness_hist: Dict[int, int] = {}
        self._dropped_stale = 0
        self._dropped_dup = 0
        self._rng = np.random.default_rng(cfg.seed)
        self._restored_from: Optional[str] = None
        self._restore()
        # A broadcast can outrun the last checkpoint: the driver passes the
        # highest epoch it ever PUBLISHED so a restarted learner never
        # re-issues an epoch the replicas would (correctly) fence forever.
        self._epoch = max(self._epoch, int(min_epoch))

    # -------------------------------------------------------- policy cores
    def _build_core(self, cfg: FleetConfig):
        if cfg.policy == "transformer":
            return _TransformerLearnerCore(cfg)
        return _MlpLearnerCore(cfg)

    # ------------------------------------------------------- checkpointing
    def _restore(self) -> None:
        from ray_tpu.train.checkpointing import (abstract_like,
                                                 latest_checkpoint,
                                                 load_checkpoint)

        path = latest_checkpoint(self._ckpt_root)
        if path is None:
            return
        state, meta = load_checkpoint(
            path, abstract_like(self._core.state()))
        self._core.load_state(state)
        self._step = int(meta["step"])
        self._epoch = int(meta.get("epoch", 0))
        self._applied_ids = set(meta.get("applied_ids", []))
        self._restored_from = path
        logger.info("fleet learner restored step=%d epoch=%d (%d applied "
                    "ids) from %s", self._step, self._epoch,
                    len(self._applied_ids), path)

    def _maybe_checkpoint(self) -> Optional[str]:
        if self._cfg.checkpoint_every <= 0:
            return None
        if self._step % self._cfg.checkpoint_every != 0:
            return None
        from ray_tpu.train.checkpointing import (gc_checkpoints,
                                                 save_checkpoint)

        path = save_checkpoint(
            self._core.state(), self._ckpt_root, self._step,
            meta={"epoch": self._epoch,
                  "applied_ids": sorted(self._applied_ids)})
        gc_checkpoints(self._ckpt_root, self._cfg.keep_checkpoints)
        return path

    # --------------------------------------------------------------- ingest
    def ingest(self, rollout_id: str, gen_epoch: int, batch) -> dict:
        """Apply one sample batch exactly once. `batch` arrives as a
        materialized top-level ObjectRef arg (zero-copy plane: same-node
        shm view, no extra copy through the serve response path)."""
        if rollout_id in self._applied_ids:
            self._dropped_dup += 1
            return {"applied": False, "reason": "duplicate",
                    "step": self._step}
        lag = max(0, self._epoch - int(gen_epoch))
        self._staleness_hist[lag] = self._staleness_hist.get(lag, 0) + 1
        if lag > self._cfg.max_staleness:
            self._dropped_stale += 1
            return {"applied": False, "reason": "stale", "lag": lag,
                    "step": self._step}
        stats = self._core.update(batch, self._rng)
        self._step += 1
        self._applied_ids.add(rollout_id)
        ckpt = self._maybe_checkpoint()
        return {"applied": True, "step": self._step, "lag": lag,
                "checkpoint": ckpt, "stats": stats}

    # -------------------------------------------------------------- weights
    def advance_epoch(self) -> dict:
        """Bump the weight epoch and return the broadcast payload. The
        driver (not the learner) owns delivery: it pushes this through
        serve.reconfigure so rolling updates and in-place pushes share one
        monotonic epoch stream."""
        self._epoch += 1
        return {"epoch": self._epoch, "weights": self._core.weights()}

    def info(self) -> dict:
        return {"step": self._step, "epoch": self._epoch,
                "applied": len(self._applied_ids),
                "dropped_stale": self._dropped_stale,
                "dropped_dup": self._dropped_dup,
                "staleness_hist": dict(self._staleness_hist),
                "restored_from": self._restored_from,
                "pid": os.getpid()}


class _MlpLearnerCore:
    """PPO update loop over env-rollout batches."""

    def __init__(self, cfg: FleetConfig):
        from ray_tpu.rllib.ppo import PPOLearner

        self._cfg = cfg
        self._learner = PPOLearner(obs_dim=4, num_actions=2, lr=cfg.lr,
                                   seed=cfg.seed)

    def state(self):
        return {"params": self._learner.params,
                "opt_state": self._learner.opt_state}

    def load_state(self, state) -> None:
        import jax
        import jax.numpy as jnp

        self._learner.params = jax.tree_util.tree_map(
            jnp.asarray, state["params"])
        self._learner.opt_state = jax.tree_util.tree_map(
            jnp.asarray, state["opt_state"])

    def weights(self):
        return self._learner.get_weights()

    def update(self, batch, rng) -> Dict[str, float]:
        from ray_tpu.rllib.ppo import compute_gae

        adv, ret = compute_gae(batch, self._cfg.gamma, self._cfg.lam)
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        T, N = batch["rewards"].shape
        flat = {
            "obs": batch["obs"].reshape(T * N, -1),
            "actions": batch["actions"].reshape(T * N),
            "logp": batch["logp"].reshape(T * N),
            "advantages": adv.reshape(T * N).astype(np.float32),
            "returns": ret.reshape(T * N).astype(np.float32),
        }
        return self._learner.update_minibatches(
            flat, self._cfg.sgd_epochs, self._cfg.minibatch_size, rng)


class _TransformerLearnerCore:
    """Next-token LM step over decode-engine token batches."""

    def __init__(self, cfg: FleetConfig):
        import jax
        import optax

        from ray_tpu.models import ModelConfig, init_params
        from ray_tpu.models.transformer import loss_fn

        self._mcfg = ModelConfig.tiny()
        self._params = init_params(jax.random.PRNGKey(cfg.seed), self._mcfg)
        self._opt = optax.adam(cfg.lr)
        self._opt_state = self._opt.init(self._params)

        def step(params, opt_state, batch):
            (l, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch, self._mcfg)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, l

        self._step_fn = jax.jit(step)

    def state(self):
        return {"params": self._params, "opt_state": self._opt_state}

    def load_state(self, state) -> None:
        import jax
        import jax.numpy as jnp

        self._params = jax.tree_util.tree_map(jnp.asarray, state["params"])
        self._opt_state = jax.tree_util.tree_map(
            jnp.asarray, state["opt_state"])

    def weights(self):
        import jax

        return jax.tree_util.tree_map(
            np.asarray, jax.device_get(self._params))

    def update(self, batch, rng) -> Dict[str, float]:
        tokens = np.asarray(batch["tokens"], np.int32)
        lm_batch = {"inputs": tokens[:, :-1], "targets": tokens[:, 1:]}
        self._params, self._opt_state, loss = self._step_fn(
            self._params, self._opt_state, lm_batch)
        return {"total_loss": float(loss)}


FleetLearner = ray_tpu.remote(FleetLearnerImpl)


# ----------------------------------------------------------------- the driver


@dataclasses.dataclass
class IngestOutcome:
    applied: int = 0
    duplicate: int = 0
    stale: int = 0
    partition_dropped: int = 0   # gave up after ingest_deadline_s
    retries: int = 0


class FleetDriver:
    """Owns the loop: deploy the rollout fleet, (re)create the named
    learner actor, and iterate sample -> ingest -> broadcast. All fault
    points live HERE (one process, one injector): the driver mediates both
    boundaries, so `partition:learner|replicas` starves exactly what a real
    network blackhole between the planes would."""

    def __init__(self, cfg: FleetConfig, ckpt_root: str):
        self.cfg = cfg
        self.ckpt_root = ckpt_root
        # harness hook: set to abort retry loops early (abandoned serve
        # futures still resolve typed via the deadline reaper — no hangs)
        self.stop_event = threading.Event()
        self.outcomes = IngestOutcome()
        # staleness lag per ingest verdict, aggregated HERE because the
        # learner's in-memory histogram resets on crash-restart
        self.staleness_hist: Dict[int, int] = {}
        self.broadcasts = 0
        self.broadcast_failures = 0
        self.last_broadcast_epoch = 0   # highest epoch ever PUBLISHED
        self.learner_restarts = 0
        self.recovery_s: List[float] = []
        self.sample_failures = 0
        self._handle = None
        self._learner = None
        define_fleet_groups()

    # ----------------------------------------------------------- lifecycle
    def start(self):
        dep = rollout_deployment(self.cfg)
        self._handle = serve.run(
            dep.bind(dataclasses.asdict(self.cfg)),
            name=self.cfg.deployment_name)
        self._sample_handle = self._handle.options(
            method_name="sample", timeout_s=self.cfg.sample_timeout_s)
        self.ensure_learner()
        # prime the fleet so replicas can sample at all
        self.broadcast(require_all=True)
        return self._handle

    def ensure_learner(self, was_restart: bool = False):
        """Connect to (or [re]create) the named learner actor."""
        try:
            self._learner = ray_tpu.get_actor(LEARNER_ACTOR_NAME)
            return self._learner
        except ValueError:
            pass
        t0 = time.monotonic()
        self._learner = FleetLearner.options(
            name=LEARNER_ACTOR_NAME).remote(
                dataclasses.asdict(self.cfg), self.ckpt_root,
                min_epoch=self.last_broadcast_epoch)
        # block until constructed (restore included) so recovery time is
        # honest: measured to a *usable* learner, not an enqueued actor
        ray_tpu.get(self._learner.info.remote(), timeout=120)
        if was_restart:
            self.learner_restarts += 1
            self.recovery_s.append(time.monotonic() - t0)
        return self._learner

    def stop(self):
        self.stop_event.set()
        try:
            serve.delete(self.cfg.deployment_name)
        except Exception:
            logger.debug("fleet deployment delete lost", exc_info=True)
        try:
            learner = ray_tpu.get_actor(LEARNER_ACTOR_NAME)
            ray_tpu.kill(learner, no_restart=True)
        except Exception:
            pass

    # ------------------------------------------------------------ the loop
    def sample_round(self) -> List[dict]:
        """Fan one sample() per target replica through the handle (the
        router spreads them; mid-request failover covers replica kills).
        Returns the envelopes that resolved."""
        with tracing.span("sample_round", "rl_sample",
                          replicas=self.cfg.num_replicas):
            futs = [self._sample_handle.remote()
                    for _ in range(self.cfg.num_replicas)]
        out = []
        for f in futs:
            if self.stop_event.is_set():
                break  # abandoned futures resolve typed (deadline reaper)
            try:
                env = ray_tpu.get(f, timeout=self.cfg.sample_timeout_s)
                if env.get("rollout_id") is not None:
                    out.append(env)
            except Exception:
                # replica kill beyond the retry budget / drain window —
                # the round simply yields fewer batches
                self.sample_failures += 1
                logger.info("sample round lost a batch", exc_info=True)
        return out

    def ingest(self, envelope: dict) -> Optional[dict]:
        """Deliver one envelope to the learner, riding out partitions
        (retry+backoff up to ingest_deadline_s) and learner crashes
        (recreate the named actor, then retry — dedupe/checkpoint make the
        retry exactly-once). Returns the learner's verdict, or None if the
        batch was abandoned at the deadline."""
        deadline = time.monotonic() + self.cfg.ingest_deadline_s
        while True:
            try:
                # the partitionable boundary: replicas-plane -> learner-plane
                fault_point(INGEST_FAULT_POINT,
                            origin=REPLICA_GROUP, dest=LEARNER_GROUP)
                with tracing.span("ingest", "rl_ingest",
                                  rollout_id=envelope["rollout_id"],
                                  weight_epoch=envelope["weight_epoch"]):
                    res = ray_tpu.get(
                        self._learner.ingest.remote(
                            envelope["rollout_id"],
                            envelope["weight_epoch"], envelope["ref"]),
                        timeout=self.cfg.ingest_timeout_s)
            except RpcDisconnected:
                if (self.stop_event.is_set()
                        or time.monotonic() > deadline):
                    self.outcomes.partition_dropped += 1
                    return None
                self.outcomes.retries += 1
                time.sleep(self.cfg.ingest_backoff_s)
                continue
            except Exception:
                if (self.stop_event.is_set()
                        or time.monotonic() > deadline):
                    self.outcomes.partition_dropped += 1
                    return None
                logger.info("learner ingest failed; reconnecting",
                            exc_info=True)
                self.outcomes.retries += 1
                time.sleep(self.cfg.ingest_backoff_s)
                try:
                    self.ensure_learner(was_restart=True)
                except Exception:
                    logger.info("learner recreate failed; will retry",
                                exc_info=True)
                continue
            if "lag" in res:
                self.staleness_hist[res["lag"]] = (
                    self.staleness_hist.get(res["lag"], 0) + 1)
            if res.get("applied"):
                self.outcomes.applied += 1
            elif res.get("reason") == "duplicate":
                self.outcomes.duplicate += 1
            elif res.get("reason") == "stale":
                self.outcomes.stale += 1
            return res

    def broadcast(self, require_all: bool = False) -> bool:
        """Pull the next epoch's weights from the learner and push them
        through the serve lightweight-update path. Partitioned broadcasts
        retry inside the ingest deadline; the epoch was already consumed,
        so a lost broadcast simply widens observed staleness (bounded by
        max_staleness at the learner)."""
        deadline = time.monotonic() + self.cfg.ingest_deadline_s
        payload = None
        while payload is None:
            try:
                payload = ray_tpu.get(self._learner.advance_epoch.remote(),
                                      timeout=self.cfg.ingest_timeout_s)
            except Exception:
                if (self.stop_event.is_set()
                        or time.monotonic() > deadline):
                    self.broadcast_failures += 1
                    return False
                time.sleep(self.cfg.ingest_backoff_s)
                try:
                    self.ensure_learner(was_restart=True)
                except Exception:
                    pass
        self.last_broadcast_epoch = max(self.last_broadcast_epoch,
                                        int(payload["epoch"]))
        while True:
            try:
                # the partitionable boundary: learner-plane -> replicas-plane
                fault_point(WEIGHTS_FAULT_POINT,
                            origin=LEARNER_GROUP, dest=REPLICA_GROUP)
                with tracing.span("broadcast_weights", "rl_broadcast",
                                  epoch=int(payload["epoch"])):
                    ok = serve.reconfigure(self.cfg.deployment_name,
                                           payload)
                self.broadcasts += 1
                if require_all and not ok:
                    # a fresh fleet must not sample weightless: re-push
                    # until every replica acked the priming epoch
                    raise RpcDisconnected("priming broadcast incomplete")
                return ok
            except (RpcDisconnected, KeyError, OSError, TimeoutError):
                if (self.stop_event.is_set()
                        or time.monotonic() > deadline):
                    self.broadcast_failures += 1
                    return False
                time.sleep(self.cfg.ingest_backoff_s)

    def train_round(self) -> Dict[str, Any]:
        """One loop iteration: sample the fleet, ingest every envelope,
        broadcast per `broadcast_every`. Returns round metrics.

        Each round roots its OWN trace (tracing_enabled): the sample fan-out
        through the serve router, every learner ingest (retries included),
        and the weight broadcast all hang off one round span — the
        rollout->learner loop reads as a single causal tree per round."""
        t0 = time.monotonic()
        round_ctx = (tracing.new_id(), "") if tracing.enabled() else None
        with tracing.ctx_scope(round_ctx), \
                tracing.span("train_round", "rl_round"):
            envelopes = self.sample_round()
            applied = 0
            applied_env_steps = 0
            last = None
            for env in envelopes:
                res = self.ingest(env)
                if res is not None:
                    last = res
                    if res.get("applied"):
                        applied += 1
                        applied_env_steps += env.get("num_env_steps", 0)
            if (last is not None and self.cfg.broadcast_every > 0
                    and last.get("applied")
                    and last["step"] % self.cfg.broadcast_every == 0):
                self.broadcast()
        return {"envelopes": len(envelopes), "applied": applied,
                "applied_env_steps": applied_env_steps,
                "round_s": time.monotonic() - t0}

    def learner_info(self, timeout: float = 30.0) -> dict:
        return ray_tpu.get(self._learner.info.remote(), timeout=timeout)

    def fence_stats(self, timeout: float = 30.0) -> List[dict]:
        h = self._handle.options(method_name="fence_stats",
                                 timeout_s=timeout)
        futs = [h.remote() for _ in range(self.cfg.num_replicas * 2)]
        stats: Dict[int, dict] = {}
        for f in futs:
            try:
                s = ray_tpu.get(f, timeout=timeout)
                stats[s["pid"]] = s
            except Exception:
                pass
        return list(stats.values())
