"""Periodic deterministic evaluation (reference `Algorithm.evaluate`,
`rllib/algorithms/algorithm.py:847`, driven by `evaluation_interval` at
`:775`).

The reference runs a dedicated evaluation WorkerSet; here evaluation rides
the existing rollout workers (the reference's
`evaluation_num_workers=0` in-place mode): each worker runs greedy
episodes on a FRESH env instance (its training envs and episode state are
untouched), so no extra actors sit idle between eval rounds.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


def run_eval_episodes(env_maker, module, params, num_episodes: int,
                      seed: int, max_steps_per_episode: int = 1000
                      ) -> Dict[str, Any]:
    """Greedy (deterministic) episodes with the module's inference path.
    Returns per-episode returns and lengths."""
    from ray_tpu.rllib.connectors import (ArgmaxAction, CastObsFloat32,
                                          ConnectorPipeline)
    from ray_tpu.rllib.env import VectorEnv

    vec = VectorEnv(env_maker, 1, seed)
    to_module = ConnectorPipeline([CastObsFloat32()])
    to_env = ConnectorPipeline([ArgmaxAction()])
    returns, lengths = [], []
    rng = np.random.default_rng(seed)  # pipeline contract; unused greedily
    for _ in range(num_episodes):
        obs = vec.reset()
        total, steps = 0.0, 0
        for _ in range(max_steps_per_episode):
            data = {"obs": obs, "module": module, "params": params,
                    "rng": rng}
            data = to_module(data)
            data["fwd_out"] = module.forward_inference(params, data["obs"])
            data = to_env(data)
            obs, rewards, dones, _ = vec.step(data["actions"])
            total += float(rewards[0])
            steps += 1
            if dones[0]:
                break
        returns.append(total)
        lengths.append(steps)
    return {"episode_returns": np.asarray(returns, np.float32),
            "episode_lengths": np.asarray(lengths, np.int32)}


class EvalConfigMixin:
    """Builder surface for evaluation settings (reference
    `AlgorithmConfig.evaluation`). Class-level defaults so config
    __init__s need no change."""

    evaluation_interval: Optional[int] = None   # iterations between evals
    evaluation_duration: int = 5                # episodes per eval

    def evaluation(self, *, evaluation_interval: Optional[int] = None,
                   evaluation_duration: Optional[int] = None):
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_duration is not None:
            self.evaluation_duration = evaluation_duration
        return self


def summarize_eval(parts) -> Dict[str, Any]:
    rets = np.concatenate([p["episode_returns"] for p in parts])
    lens = np.concatenate([p["episode_lengths"] for p in parts])
    return {
        "episode_reward_mean": float(rets.mean()),
        "episode_reward_min": float(rets.min()),
        "episode_reward_max": float(rets.max()),
        "episode_len_mean": float(lens.mean()),
        "num_episodes": int(len(rets)),
    }
