"""SimpleQ: the minimal Q-learning baseline.

Mirrors the reference's SimpleQ (`rllib/algorithms/simple_q/simple_q.py`):
DQN stripped to its core — plain max-over-target-net TD backup (no double
estimation), uniform replay, one update per round. Implemented as the DQN
anatomy with `double_q=False` and the reference's SimpleQ defaults, the
same way the reference derives DQN by EXTENDING SimpleQ.
"""

from __future__ import annotations

from ray_tpu.rllib.dqn import DQN, DQNConfig


class SimpleQConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.double_q = False
        self.prioritized_replay = False
        self.num_updates_per_step = 1
        self.target_update_interval = 8

    def build(self) -> "SimpleQ":
        return SimpleQ({"dqn_config": self})


class SimpleQ(DQN):
    """SimpleQ = DQN minus the double-Q estimator (reference simple_q)."""
