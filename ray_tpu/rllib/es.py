"""Evolution Strategies (Salimans et al. 2017).

Mirrors the reference's ES (`rllib/algorithms/es/es.py`): a fleet of
evaluation actors, each episode scored under a seed-indexed antithetic
parameter perturbation; the driver reconstructs every perturbation from
its integer seed (only seeds and returns travel) and applies the
rank-normalized ES gradient estimate.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import CartPoleEnv


def _flatten(params: Dict[str, np.ndarray]) -> Tuple[np.ndarray, List]:
    keys = sorted(params)
    shapes = [(k, params[k].shape) for k in keys]
    flat = np.concatenate([params[k].ravel() for k in keys])
    return flat.astype(np.float32), shapes


def _unflatten(flat: np.ndarray, shapes: List) -> Dict[str, np.ndarray]:
    out, i = {}, 0
    for k, shape in shapes:
        n = int(np.prod(shape))
        out[k] = flat[i:i + n].reshape(shape).astype(np.float32)
        i += n
    return out


from ray_tpu.rllib.models import init_mlp, mlp_forward_np


def _mlp_policy(obs_dim: int, num_actions: int, hidden=(32, 32), seed=0):
    return init_mlp(np.random.default_rng(seed), (obs_dim, *hidden, num_actions))


def _act(params: Dict[str, np.ndarray], obs: np.ndarray) -> int:
    return int(np.argmax(mlp_forward_np(params, obs)))


@ray_tpu.remote
class ESEvalWorker:
    """Evaluates perturbed policies; perturbations regenerate from seeds."""

    def __init__(self, env_maker, hidden: tuple, noise_std: float):
        self.env_maker = env_maker
        self.noise_std = noise_std

    def evaluate(self, flat: np.ndarray, shapes: List,
                 noise_seeds: List[int], max_steps: int) -> List[Tuple[int, float, float]]:
        """For each seed: antithetic pair of episode returns (+eps, -eps)."""
        out = []
        for s in noise_seeds:
            eps = np.random.default_rng(s).standard_normal(len(flat)).astype(np.float32)
            r_pos = self._rollout(flat + self.noise_std * eps, shapes, max_steps, s)
            r_neg = self._rollout(flat - self.noise_std * eps, shapes, max_steps, s + 1)
            out.append((s, r_pos, r_neg))
        return out

    def _rollout(self, flat, shapes, max_steps: int, ep_seed: int) -> float:
        params = _unflatten(flat, shapes)
        env = self.env_maker(ep_seed)
        obs = env.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done, _ = env.step(_act(params, obs))
            total += r
            if done:
                break
        return total


class ESConfig:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: CartPoleEnv(seed)
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.hidden = (32, 32)
        self.num_workers = 2
        self.episodes_per_batch = 16     # perturbation pairs per iteration
        self.noise_std = 0.05
        self.lr = 0.02
        self.max_episode_steps = 500
        self.seed = 0

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown ES option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "ES":
        return ES({"es_config": self})


class ES(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        cfg: ESConfig = config.get("es_config") or ESConfig()
        self.cfg = cfg
        params = _mlp_policy(cfg.obs_dim, cfg.num_actions, cfg.hidden, cfg.seed)
        self.flat, self.shapes = _flatten(params)
        self.workers = [
            ESEvalWorker.options(num_cpus=1).remote(
                cfg.env_maker, cfg.hidden, cfg.noise_std)
            for i in range(cfg.num_workers)]
        self._seed_counter = 1000

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        seeds = [self._seed_counter + 2 * i
                 for i in range(cfg.episodes_per_batch)]
        self._seed_counter += 2 * cfg.episodes_per_batch + 2
        chunks = np.array_split(np.asarray(seeds), len(self.workers))
        futures = [
            w.evaluate.remote(self.flat, self.shapes, c.tolist(),
                              cfg.max_episode_steps)
            for w, c in zip(self.workers, chunks) if len(c)]
        results = [r for chunk in ray_tpu.get(futures) for r in chunk]

        returns = np.array([[rp, rn] for _, rp, rn in results], np.float32)
        # rank normalization (reference es.py compute_centered_ranks)
        flat_ranks = returns.ravel().argsort().argsort().astype(np.float32)
        ranks = flat_ranks.reshape(returns.shape)
        ranks = ranks / (ranks.size - 1) - 0.5
        grad = np.zeros_like(self.flat)
        for (s, _, _), (w_pos, w_neg) in zip(results, ranks):
            eps = np.random.default_rng(s).standard_normal(
                len(self.flat)).astype(np.float32)
            grad += (w_pos - w_neg) * eps
        grad /= (len(results) * cfg.noise_std)
        self.flat = self.flat + cfg.lr * grad
        return {
            "episode_reward_mean": float(returns.mean()),
            "episode_reward_max": float(returns.max()),
            "num_episodes": int(returns.size),
        }

    def get_weights(self):
        return {"flat": self.flat.copy(), "shapes": self.shapes}

    def set_weights(self, weights) -> None:
        self.flat = np.asarray(weights["flat"], np.float32).copy()
        self.shapes = weights["shapes"]

    def stop(self) -> None:
        self._kill_workers(self.workers)
