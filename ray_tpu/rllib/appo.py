"""APPO: asynchronous PPO (IMPALA-style sampling + clipped surrogate).

Mirrors the reference's APPO (`rllib/algorithms/appo/appo.py`): the IMPALA
async actor-learner control flow, but the learner optimizes the PPO
clipped-surrogate objective against the *behavior* policy's log-probs,
with V-trace value targets correcting policy lag. One jitted update.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import CartPoleEnv
from ray_tpu.rllib.impala import _VTraceLearner
from ray_tpu.rllib.ppo import RolloutWorker


class APPOLearner(_VTraceLearner):
    """Clipped-surrogate loss on v-trace advantages (reference
    rllib/algorithms/appo)."""

    def __init__(self, obs_dim: int, num_actions: int, lr: float,
                 gamma: float, clip: float, vf_coeff: float,
                 entropy_coeff: float, seed: int = 0, mesh=None, module=None):
        self._clip = clip
        super().__init__(obs_dim, num_actions, lr, gamma, vf_coeff,
                         entropy_coeff, seed=seed, mesh=mesh, module=module)

    def loss(self, params, batch, extra, rng):
        import jax
        import jax.numpy as jnp

        tm, dist, logp, values, vs, pg_adv = self._policy_terms(params, batch)
        adv = jax.lax.stop_gradient(pg_adv)
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        ratio = jnp.exp(logp - tm["logp"])
        pg = -jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - self._clip, 1 + self._clip) * adv).mean()
        vf = 0.5 * ((values - jax.lax.stop_gradient(vs)) ** 2).mean()
        entropy = dist.entropy().mean()
        total = pg + self._vf_coeff * vf - self._entropy_coeff * entropy
        return total, {"policy_loss": pg, "vf_loss": vf, "entropy": entropy}


class APPOConfig:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: CartPoleEnv(seed)
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 4
        self.rollout_fragment_length = 64
        self.lr = 5e-4
        self.gamma = 0.99
        self.clip_param = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.max_inflight = 2
        self.seed = 0

    def environment(self, env_maker=None, *, obs_dim=None, num_actions=None):
        if env_maker is not None:
            self.env_maker = env_maker
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def rollouts(self, *, num_rollout_workers=None, num_envs_per_worker=None,
                 rollout_fragment_length=None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown APPO option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "APPO":
        return APPO({"appo_config": self})


class APPO(Algorithm):
    """Async actor-learner with PPO-clip updates on stale batches."""

    def setup(self, config: Dict[str, Any]) -> None:
        cfg: APPOConfig = config.get("appo_config") or APPOConfig()
        self.cfg = cfg
        self.learner = APPOLearner(
            cfg.obs_dim, cfg.num_actions, cfg.lr, cfg.gamma, cfg.clip_param,
            cfg.vf_coeff, cfg.entropy_coeff, cfg.seed)
        self.workers = [
            RolloutWorker.options(num_cpus=1).remote(
                cfg.env_maker, cfg.num_envs_per_worker,
                cfg.seed + 1000 * (i + 1), cfg.obs_dim, cfg.num_actions)
            for i in range(cfg.num_rollout_workers)]
        w = self.learner.get_weights()
        ray_tpu.get([wk.set_weights.remote(w) for wk in self.workers])
        self._inflight: Dict[Any, int] = {}
        for i, wk in enumerate(self.workers):
            for _ in range(cfg.max_inflight):
                self._inflight[wk.sample.remote(
                    cfg.rollout_fragment_length)] = i
        self._reward_history: List[float] = []
        self._total_steps = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        ready, _ = ray_tpu.wait(
            list(self._inflight), num_returns=1, timeout=None)
        stats: Dict[str, float] = {}
        steps = 0
        for fut in ready:
            widx = self._inflight.pop(fut)
            batch = ray_tpu.get(fut)
            self._reward_history.extend(batch["episode_returns"].tolist())
            self._reward_history = self._reward_history[-100:]
            stats = self.learner.update_batch({
                k: batch[k] for k in
                ("obs", "actions", "logp", "rewards", "dones", "last_value")})
            steps += int(batch["actions"].size)
            self._total_steps += int(batch["actions"].size)
            wk = self.workers[widx]
            wk.set_weights.remote(self.learner.get_weights())
            self._inflight[wk.sample.remote(cfg.rollout_fragment_length)] = widx
        return {
            "episode_reward_mean": (float(np.mean(self._reward_history))
                                    if self._reward_history else 0.0),
            "num_env_steps_sampled": self._total_steps,
            **stats,
        }

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        self.learner.set_weights(weights)

    def stop(self) -> None:
        self._kill_workers(self.workers)
