"""Ape-X DQN: distributed prioritized experience replay.

Mirrors the reference's APEX anatomy (`rllib/algorithms/apex_dqn/`):
a fleet of epsilon-greedy rollout workers with a *per-worker epsilon
ladder* (worker i explores at eps^(1 + i/(N-1)*alpha)), transitions flow
into a replay *actor* (off the driver — the reference shards replay across
`num_replay_buffer_shards` actors), the learner samples from replay,
updates, and pushes new priorities back; weights broadcast periodically
rather than synchronously every step.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.dqn import DQNLearner, EpsilonGreedyWorker
from ray_tpu.rllib.env import CartPoleEnv
from ray_tpu.rllib.replay_buffers import PrioritizedReplayBuffer


@ray_tpu.remote
class ReplayActor:
    """One prioritized replay shard living in its own process."""

    def __init__(self, capacity: int, alpha: float, seed: int):
        self.buffer = PrioritizedReplayBuffer(capacity, alpha=alpha, seed=seed)

    def add_batch(self, batch: Dict[str, np.ndarray]) -> int:
        self.buffer.add_batch(batch)
        return len(self.buffer)

    def sample(self, batch_size: int, beta: float):
        if len(self.buffer) < batch_size:
            return None
        return self.buffer.sample(batch_size, beta=beta)

    def update_priorities(self, idx, td) -> bool:
        self.buffer.update_priorities(idx, td)
        return True

    def size(self) -> int:
        return len(self.buffer)


class ApexDQNConfig:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: CartPoleEnv(seed)
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.num_rollout_workers = 3
        self.num_envs_per_worker = 2
        self.rollout_fragment_length = 32
        self.num_replay_shards = 1
        self.lr = 5e-4
        self.gamma = 0.99
        self.buffer_capacity = 50_000
        self.replay_alpha = 0.6
        self.replay_beta = 0.4
        self.train_batch_size = 64
        self.num_updates_per_step = 8
        self.target_update_interval = 4      # in training_steps
        self.broadcast_interval = 1          # weight push cadence
        self.base_epsilon = 0.4              # ladder: eps^(1 + i/(N-1)*7)
        self.epsilon_alpha = 7.0
        self.learning_starts = 200
        self.seed = 0

    def rollouts(self, *, num_rollout_workers=None, num_envs_per_worker=None,
                 rollout_fragment_length=None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown ApexDQN option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "ApexDQN":
        return ApexDQN({"apex_config": self})


class ApexDQN(Algorithm):
    """The Ape-X anatomy as an extensible template: `_make_learner`,
    `_make_workers`, `_issue_sample`, `_learner_update`, and
    `_maybe_sync_target` are the algorithm-specific seams ApexDDPG
    overrides (the reference derives apex_ddpg from apex_dqn the same
    way)."""

    def setup(self, config: Dict[str, Any]) -> None:
        cfg = config.get("apex_config") or ApexDQNConfig()
        self.cfg = cfg
        self.learner = self._make_learner(cfg)
        self.replays = [
            ReplayActor.options(num_cpus=1).remote(
                cfg.buffer_capacity // cfg.num_replay_shards,
                cfg.replay_alpha, cfg.seed + i)
            for i in range(cfg.num_replay_shards)]
        self.workers = self._make_workers(cfg)
        self._broadcast()
        self._reward_history: List[float] = []
        self._total_steps = 0
        self._buffered = 0
        self._pending: Dict[Any, int] = {}  # sample future -> worker index

    # ------------------------------------------------------- subclass seams
    def _make_learner(self, cfg):
        return DQNLearner(cfg.obs_dim, cfg.num_actions, cfg.lr,
                          cfg.gamma, cfg.seed)

    def _make_workers(self, cfg) -> List[Any]:
        self._epsilons = self._epsilon_ladder(cfg.num_rollout_workers)
        return [
            EpsilonGreedyWorker.options(num_cpus=1).remote(
                cfg.env_maker, cfg.num_envs_per_worker,
                cfg.seed + 1000 * (i + 1), cfg.obs_dim, cfg.num_actions)
            for i in range(cfg.num_rollout_workers)]

    def _issue_sample(self, i: int, wk):
        return wk.sample.remote(self.cfg.rollout_fragment_length,
                                self._epsilons[i])

    def _learner_update(self, batch):
        """One update; returns (loss, |td| priorities)."""
        loss, td = self.learner.update_batch(batch)
        return loss, np.abs(td)

    def _maybe_sync_target(self) -> None:
        if self.iteration % self.cfg.target_update_interval == 0:
            self.learner.sync_target()

    def _extra_stats(self) -> Dict[str, Any]:
        return {"epsilons": list(self._epsilons)}

    # -------------------------------------------------------------- driver
    def _epsilon_ladder(self, n: int) -> List[float]:
        cfg = self.cfg
        if n == 1:
            return [cfg.base_epsilon]
        return [cfg.base_epsilon ** (1.0 + i / (n - 1) * cfg.epsilon_alpha)
                for i in range(n)]

    def _broadcast(self) -> None:
        from ray_tpu.rllib.learner import broadcast_weights

        broadcast_weights(self.learner.get_weights(), self.workers)

    def _shard_for(self, i: int):
        return self.replays[i % len(self.replays)]

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        # keep one in-flight sample per worker; harvest only what is ready
        # so rollout collection overlaps with the learner's update loop
        for i, wk in enumerate(self.workers):
            if not any(w == i for w in self._pending.values()):
                self._pending[self._issue_sample(i, wk)] = i
        sizes = ray_tpu.get([r.size.remote() for r in self.replays])
        ready, _ = ray_tpu.wait(list(self._pending),
                                num_returns=len(self._pending), timeout=0.05)
        if not ready and sum(sizes) < cfg.learning_starts:
            # nothing buffered yet: block for the first fragment
            ready, _ = ray_tpu.wait(list(self._pending), num_returns=1,
                                    timeout=30)
        store_futs = []
        n_stored = 0
        for fut in ready:
            i = self._pending.pop(fut)
            s = ray_tpu.get(fut)
            ep = s.pop("episode_returns")
            self._reward_history.extend(ep.tolist())
            self._total_steps += len(s["actions"])
            n_stored += len(s["actions"])
            store_futs.append(self._shard_for(i).add_batch.remote(s))
        ray_tpu.get(store_futs)
        self._reward_history = self._reward_history[-100:]

        self._buffered = int(sum(sizes) + n_stored)
        losses = []
        if self._buffered >= cfg.learning_starts:
            for u in range(cfg.num_updates_per_step):
                shard = self.replays[u % len(self.replays)]
                batch = ray_tpu.get(shard.sample.remote(
                    cfg.train_batch_size, cfg.replay_beta))
                if batch is None:
                    continue
                idx = batch.pop("batch_indexes")
                loss, priorities = self._learner_update(batch)
                losses.append(loss)
                shard.update_priorities.remote(idx, priorities)
            self._maybe_sync_target()
            if self.iteration % cfg.broadcast_interval == 0:
                self._broadcast()
        return {
            "episode_reward_mean": (float(np.mean(self._reward_history))
                                    if self._reward_history else 0.0),
            "buffer_size": self._buffered,
            "num_env_steps_sampled": self._total_steps,
            "loss": float(np.mean(losses)) if losses else float("nan"),
            **self._extra_stats(),
        }

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        self.learner.set_weights(weights)
        self._broadcast()

    def stop(self) -> None:
        self._kill_workers(self.workers + self.replays)


class ApexDDPGConfig:
    """Ape-X architecture around the DDPG learner
    (reference `rllib/algorithms/apex_ddpg/apex_ddpg.py`)."""

    def __init__(self):
        from ray_tpu.rllib.env import PendulumEnv

        self.env_maker: Callable[[int], Any] = lambda seed: PendulumEnv(seed)
        self.obs_dim = PendulumEnv.observation_dim
        self.action_dim = PendulumEnv.action_dim
        self.max_action = PendulumEnv.max_action
        self.num_rollout_workers = 3
        self.num_envs_per_worker = 1
        self.rollout_fragment_length = 32
        self.num_replay_shards = 1
        self.actor_lr = 1e-3
        self.critic_lr = 1e-3
        self.gamma = 0.99
        self.tau = 0.005
        self.twin_q = False
        self.buffer_capacity = 100_000
        self.replay_alpha = 0.6
        self.replay_beta = 0.4
        self.train_batch_size = 128
        self.num_updates_per_step = 8
        self.broadcast_interval = 1
        # per-worker exploration-noise ladder (the continuous analog of
        # Ape-X's epsilon ladder): worker i explores at base^(1+i/(N-1)*a)
        self.base_noise = 0.4
        self.noise_alpha = 3.0
        self.learning_starts = 256
        self.seed = 0

    def rollouts(self, *, num_rollout_workers=None, num_envs_per_worker=None,
                 rollout_fragment_length=None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown ApexDDPG option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "ApexDDPG":
        return ApexDDPG({"apex_config": self})


class ApexDDPG(ApexDQN):
    """Distributed prioritized replay + DDPG: noise-laddered continuous
    actors feed replay shards; the learner polyak-syncs its targets inside
    the jitted update, so there is no explicit target-sync step."""

    def _make_learner(self, cfg):
        from ray_tpu.rllib.ddpg import DDPGLearner

        return DDPGLearner(
            cfg.obs_dim, cfg.action_dim, cfg.max_action, cfg.actor_lr,
            cfg.critic_lr, cfg.gamma, cfg.tau, cfg.twin_q,
            smooth_target_policy=False, target_noise=0.0,
            target_noise_clip=0.0, seed=cfg.seed)

    def _make_workers(self, cfg) -> List[Any]:
        from ray_tpu.rllib.ddpg import NoisyActorWorker

        if cfg.num_rollout_workers == 1:
            noises = [cfg.base_noise]
        else:
            n = cfg.num_rollout_workers
            noises = [cfg.base_noise ** (1.0 + i / (n - 1) * cfg.noise_alpha)
                      for i in range(n)]
        self._noises = noises
        return [
            NoisyActorWorker.options(num_cpus=1).remote(
                cfg.env_maker, cfg.num_envs_per_worker,
                cfg.seed + 1000 * (i + 1), cfg.obs_dim, cfg.action_dim,
                cfg.max_action, noises[i])
            for i in range(cfg.num_rollout_workers)]

    def _issue_sample(self, i: int, wk):
        random_phase = self._buffered < self.cfg.learning_starts
        return wk.sample.remote(self.cfg.rollout_fragment_length,
                                random_phase)

    def _learner_update(self, batch):
        import jax

        keys = ("obs", "actions", "rewards", "next_obs", "dones", "weights")
        aux = jax.device_get(self.learner.update(
            {k: batch[k] for k in keys if k in batch}))
        return float(aux["total_loss"]), np.abs(np.asarray(aux["td"]))

    def _maybe_sync_target(self) -> None:
        pass  # polyak sync rides the jitted post_update hook

    def _broadcast(self) -> None:
        from ray_tpu.rllib.learner import broadcast_weights

        broadcast_weights(self.learner.get_weights()["actor"], self.workers)

    def _extra_stats(self) -> Dict[str, Any]:
        return {"noise_scales": list(self._noises)}
