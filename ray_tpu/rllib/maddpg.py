"""MADDPG: multi-agent DDPG with centralized critics (Lowe et al. 2017).

Reference parity: rllib/algorithms/maddpg/ (SURVEY §2.3 algorithm list).
Each agent owns a decentralized actor mu_i(o_i) but a *centralized* critic
Q_i(o_1..o_N, a_1..a_N) trained off a shared replay buffer — the standard
fix for non-stationarity in continuous multi-agent control. Actors and
critics are jitted JAX updates; rollouts step a cooperative continuous env
in-process (the env is cheap; the fleet pattern lives in ddpg.py).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.models import init_mlp, mlp_forward
from ray_tpu.rllib.replay_buffers import ReplayBuffer


class SpreadEnv:
    """Cooperative continuous env: N agents on a line must cover N distinct
    landmarks. obs_i = [own pos, all landmark offsets]; action_i = velocity
    in [-1, 1]. Shared reward = -sum_k min_i |pos_i - landmark_k| — a 1-D
    simple-spread (the MADDPG paper's benchmark family)."""

    def __init__(self, seed: int = 0, n_agents: int = 2,
                 episode_len: int = 25):
        self.n = n_agents
        self.rng = np.random.default_rng(seed)
        self.episode_len = episode_len
        self.obs_dim = 1 + n_agents  # own pos + landmark offsets
        self.action_dim = 1
        self.max_action = 1.0
        self.agents = [f"agent_{i}" for i in range(n_agents)]

    def _obs(self) -> Dict[str, np.ndarray]:
        return {
            a: np.concatenate(
                [[self.pos[i]], self.landmarks - self.pos[i]]
            ).astype(np.float32)
            for i, a in enumerate(self.agents)
        }

    def reset(self) -> Dict[str, np.ndarray]:
        self.pos = self.rng.uniform(-1, 1, self.n)
        self.landmarks = np.sort(self.rng.uniform(-1, 1, self.n))
        self.t = 0
        return self._obs()

    def step(self, actions: Dict[str, np.ndarray]):
        for i, a in enumerate(self.agents):
            self.pos[i] = np.clip(
                self.pos[i] + 0.1 * float(np.asarray(actions[a]).ravel()[0]),
                -2, 2)
        # each landmark scored by its nearest agent
        dists = np.abs(self.pos[:, None] - self.landmarks[None, :])
        reward = -float(dists.min(axis=0).sum())
        self.t += 1
        done = self.t >= self.episode_len
        obs = self._obs()
        rewards = {a: reward for a in self.agents}
        dones = {a: done for a in self.agents}
        dones["__all__"] = done
        return obs, rewards, dones, {}


class MADDPGConfig:
    def __init__(self):
        self.env_maker = lambda seed: SpreadEnv(seed)
        self.n_agents = 2
        self.obs_dim = 3  # SpreadEnv(n=2)
        self.action_dim = 1
        self.max_action = 1.0
        self.lr_actor = 1e-3
        self.lr_critic = 1e-3
        self.gamma = 0.95
        self.tau = 0.01
        self.buffer_size = 50_000
        self.batch_size = 256
        self.warmup_steps = 500
        self.expl_noise = 0.3
        self.episodes_per_iter = 10
        self.updates_per_iter = 50
        self.seed = 0

    def environment(self, env_maker=None, *, n_agents=None, obs_dim=None,
                    action_dim=None, max_action=None) -> "MADDPGConfig":
        for k, v in [("env_maker", env_maker), ("n_agents", n_agents),
                     ("obs_dim", obs_dim), ("action_dim", action_dim),
                     ("max_action", max_action)]:
            if v is not None:
                setattr(self, k, v)
        return self

    def training(self, **kw) -> "MADDPGConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown option {k!r}")
            if v is not None:
                setattr(self, k, v)
        return self

    def build(self) -> "MADDPG":
        return MADDPG({"maddpg_config": self})


class MADDPG(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import optax

        cfg: MADDPGConfig = config.get("maddpg_config") or MADDPGConfig()
        self.cfg = cfg
        N, D, A = cfg.n_agents, cfg.obs_dim, cfg.action_dim
        rng = np.random.default_rng(cfg.seed)
        joint = N * (D + A)
        self.actors = [init_mlp(rng, [D, 64, 64, A], final_scale=0.01)
                       for _ in range(N)]
        # centralized critics: Q_i over ALL obs + ALL actions
        self.critics = [init_mlp(rng, [joint, 64, 64, 1], final_scale=0.01)
                        for _ in range(N)]
        self.t_actors = [jax.tree_util.tree_map(np.copy, p)
                         for p in self.actors]
        self.t_critics = [jax.tree_util.tree_map(np.copy, p)
                          for p in self.critics]
        self.opt_a = optax.adam(cfg.lr_actor)
        self.opt_c = optax.adam(cfg.lr_critic)
        self.os_a = [self.opt_a.init(p) for p in self.actors]
        self.os_c = [self.opt_c.init(p) for p in self.critics]
        self.rng = rng
        self.env = cfg.env_maker(cfg.seed)
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._total_steps = 0
        self._reward_history: List[float] = []

        max_action = cfg.max_action

        def actor_apply(params, obs):
            import jax.numpy as jnp

            return max_action * jnp.tanh(mlp_forward(params, obs, 3))

        self._actor_apply = jax.jit(actor_apply)

        def critic_apply(params, joint_in):
            return mlp_forward(params, joint_in, 3)[..., 0]

        gamma = cfg.gamma

        def critic_loss(cp, joint_in, target_q):
            import jax.numpy as jnp

            q = critic_apply(cp, joint_in)
            return ((q - target_q) ** 2).mean()

        def critic_update(cp, os, joint_in, target_q):
            loss, grads = jax.value_and_grad(critic_loss)(
                cp, joint_in, target_q)
            updates, os = self.opt_c.update(grads, os, cp)
            return optax.apply_updates(cp, updates), os, loss

        self._critic_update = jax.jit(critic_update)

        def actor_loss(ap, cp, obs_all, act_all, i):
            # re-substitute agent i's action with its current policy output
            import jax.numpy as jnp

            my_act = actor_apply(ap, obs_all[:, i])
            act = act_all.at[:, i].set(my_act)
            B = obs_all.shape[0]
            joint_in = jnp.concatenate(
                [obs_all.reshape(B, -1), act.reshape(B, -1)], axis=1)
            return -critic_apply(cp, joint_in).mean()

        def actor_update(ap, os, cp, obs_all, act_all, i):
            loss, grads = jax.value_and_grad(actor_loss)(
                ap, cp, obs_all, act_all, i)
            updates, os = self.opt_a.update(grads, os, ap)
            return optax.apply_updates(ap, updates), os, loss

        self._actor_update = jax.jit(actor_update, static_argnums=(5,))

        tau = cfg.tau

        def soft_update(target, online):
            return jax.tree_util.tree_map(
                lambda t, o: (1 - tau) * t + tau * o, target, online)

        self._soft_update = jax.jit(soft_update)

        def target_actions(t_actors, next_obs_all):
            import jax.numpy as jnp

            return jnp.stack(
                [actor_apply(p, next_obs_all[:, i])
                 for i, p in enumerate(t_actors)], axis=1)

        self._target_actions = jax.jit(target_actions)

    # ------------------------------------------------------------- rollout
    def _collect_episode(self, noise: float, store: bool = True) -> float:
        """store=False rolls out without touching the replay buffer or the
        sampled-step counter (pure evaluation)."""
        cfg = self.cfg
        env = self.env
        obs = env.reset()
        total = 0.0
        while True:
            obs_arr = np.stack([obs[a] for a in env.agents])
            acts = {}
            for i, a in enumerate(env.agents):
                mu = np.asarray(self._actor_apply(
                    self.actors[i], obs_arr[i][None]))[0]
                act = mu + noise * self.rng.standard_normal(cfg.action_dim)
                acts[a] = np.clip(act, -cfg.max_action, cfg.max_action)
            nxt, rewards, dones, _ = env.step(acts)
            nxt_arr = np.stack([nxt[a] for a in env.agents])
            act_arr = np.stack([acts[a] for a in env.agents])
            rew_arr = np.array([rewards[a] for a in env.agents], np.float32)
            if store:
                self.buffer.add_batch({
                    "obs": obs_arr[None], "actions": act_arr[None],
                    "rewards": rew_arr[None], "next_obs": nxt_arr[None],
                    "dones": np.array([float(dones["__all__"])],
                                      np.float32)})
                self._total_steps += 1
            total += rew_arr[0]
            obs = nxt
            if dones["__all__"]:
                return total

    def _update_once(self) -> Dict[str, float]:
        import jax.numpy as jnp

        cfg = self.cfg
        sample = self.buffer.sample(cfg.batch_size)
        obs_all = jnp.asarray(sample["obs"])  # [B,N,D]
        act_all = jnp.asarray(sample["actions"])
        rew_all = sample["rewards"]  # [B,N]
        nxt_all = jnp.asarray(sample["next_obs"])
        done = sample["dones"]

        B = cfg.batch_size
        nxt_acts = self._target_actions(self.t_actors, nxt_all)
        nxt_joint = jnp.concatenate(
            [nxt_all.reshape(B, -1), nxt_acts.reshape(B, -1)], axis=1)
        joint_in = jnp.concatenate(
            [obs_all.reshape(B, -1), act_all.reshape(B, -1)], axis=1)

        stats = {}
        for i in range(cfg.n_agents):
            tq = np.asarray(mlp_forward(self.t_critics[i], nxt_joint, 3))[:, 0]
            target_q = rew_all[:, i] + cfg.gamma * (1 - done) * tq
            self.critics[i], self.os_c[i], closs = self._critic_update(
                self.critics[i], self.os_c[i], joint_in,
                jnp.asarray(target_q))
            self.actors[i], self.os_a[i], aloss = self._actor_update(
                self.actors[i], self.os_a[i], self.critics[i],
                obs_all, act_all, i)
            self.t_actors[i] = self._soft_update(
                self.t_actors[i], self.actors[i])
            self.t_critics[i] = self._soft_update(
                self.t_critics[i], self.critics[i])
            stats[f"critic_loss_{i}"] = float(closs)
            stats[f"actor_loss_{i}"] = float(aloss)
        return stats

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        returns = [self._collect_episode(cfg.expl_noise)
                   for _ in range(cfg.episodes_per_iter)]
        stats: Dict[str, float] = {}
        if self._total_steps >= cfg.warmup_steps:
            for _ in range(cfg.updates_per_iter):
                stats = self._update_once()
        self._reward_history.extend(returns)
        self._reward_history = self._reward_history[-50:]
        return {"episode_reward_mean": float(np.mean(self._reward_history)),
                "num_env_steps_sampled": self._total_steps, **stats}

    def greedy_return(self, episodes: int = 5) -> float:
        totals = []
        for _ in range(episodes):
            totals.append(self._collect_episode(0.0, store=False))
        return float(np.mean(totals))

    def get_weights(self):
        return {"actors": self.actors, "critics": self.critics}

    def set_weights(self, weights) -> None:
        import jax

        self.actors = weights["actors"]
        self.critics = weights["critics"]
        self.t_actors = [jax.tree_util.tree_map(np.copy, p)
                         for p in self.actors]
        self.t_critics = [jax.tree_util.tree_map(np.copy, p)
                          for p in self.critics]
