"""Connector pipelines: composable env<->module data transforms.

Mirrors the reference's connector architecture (`rllib/connectors/`): the
glue between raw env observations and module inputs (env-to-module) and
between module outputs and env actions (module-to-env) is a PIPELINE of
small, swappable steps instead of logic hard-coded into each rollout
worker. An algorithm changes exploration (greedy vs. sampled vs.
eps-greedy), obs preprocessing, or action postprocessing by editing its
pipeline, not by forking the worker.

Connectors run HOST-SIDE in env-stepping actors (numpy), so steps stay
vectorized-numpy; the module's jitted forwards remain untouched.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

__all__ = [
    "Connector", "ConnectorPipeline", "CastObsFloat32", "SampleAction",
    "ArgmaxAction", "EpsilonGreedy", "GaussianNoise", "ClipAction",
    "RandomActions",
]


class Connector:
    """One transform over the rollout context dict. Mutates/returns `data`.

    Keys by convention: "obs", "fwd_out" (module forward outputs),
    "actions", "logp", "rng" (np.random.Generator), "module", "params",
    "timestep"."""

    def __call__(self, data: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError


class ConnectorPipeline(Connector):
    """Ordered composition (reference ConnectorPipelineV2). Supports
    insertion for customization: `pipeline.prepend(...)` / `append(...)`."""

    def __init__(self, steps: Optional[List[Connector]] = None):
        self.steps = list(steps or [])

    def __call__(self, data: Dict[str, Any]) -> Dict[str, Any]:
        for step in self.steps:
            data = step(data)
        return data

    def append(self, step: Connector) -> "ConnectorPipeline":
        self.steps.append(step)
        return self

    def prepend(self, step: Connector) -> "ConnectorPipeline":
        self.steps.insert(0, step)
        return self


# ------------------------------------------------------------ env-to-module


class CastObsFloat32(Connector):
    def __call__(self, data):
        data["obs"] = np.asarray(data["obs"], np.float32)
        return data


# ------------------------------------------------------------ module-to-env


class SampleAction(Connector):
    """Sample from the module's action distribution; records "logp" (what
    on-policy losses need). Off-policy pipelines that never consume logp
    (SAC/DDPG replay) pass record_logp=False to keep it off the per-step
    hot path."""

    def __init__(self, record_logp: bool = True):
        self.record_logp = record_logp

    def __call__(self, data):
        dist = data["module"].action_dist(data["fwd_out"])
        actions = dist.sample(data["rng"])
        data["actions"] = actions
        if self.record_logp:
            data["logp"] = np.asarray(dist.logp(actions), np.float32)
        return data


class ArgmaxAction(Connector):
    """Greedy action (evaluation / deterministic policies)."""

    def __call__(self, data):
        dist = data["module"].action_dist(data["fwd_out"])
        data["actions"] = dist.argmax()
        return data


class EpsilonGreedy(Connector):
    """Annealed eps-greedy over the module's argmax (DQN-family
    exploration; reference rllib/utils/exploration/epsilon_greedy.py)."""

    def __init__(self, num_actions: int, eps_start: float = 1.0,
                 eps_end: float = 0.02, anneal_steps: int = 10_000):
        self.eps_start = eps_start
        self.eps_end = eps_end
        self.anneal_steps = max(1, anneal_steps)
        self.num_actions = num_actions

    def epsilon(self, t: int) -> float:
        frac = min(1.0, t / self.anneal_steps)
        return self.eps_start + frac * (self.eps_end - self.eps_start)

    def __call__(self, data):
        dist = data["module"].action_dist(data["fwd_out"])
        greedy = dist.argmax()
        rng: np.random.Generator = data["rng"]
        # algorithms that schedule epsilon centrally (DQN anneals per
        # training iteration, not per env timestep) force it per call
        if "epsilon_override" in data:
            eps = float(data["epsilon_override"])
        else:
            eps = self.epsilon(int(data.get("timestep", 0)))
        explore = rng.random(len(greedy)) < eps
        randoms = rng.integers(0, self.num_actions, size=len(greedy))
        data["actions"] = np.where(explore, randoms, greedy).astype(np.int32)
        data["epsilon"] = eps
        return data


class GaussianNoise(Connector):
    """Additive exploration noise for continuous deterministic policies
    (DDPG/TD3)."""

    def __init__(self, scale: float, low: float, high: float):
        self.scale = scale
        self.low = low
        self.high = high

    def __call__(self, data):
        a = np.asarray(data["actions"], np.float32)
        a = a + data["rng"].normal(0.0, self.scale, a.shape).astype(np.float32)
        data["actions"] = np.clip(a, self.low, self.high)
        return data


class ClipAction(Connector):
    def __init__(self, low: float, high: float):
        self.low = low
        self.high = high

    def __call__(self, data):
        data["actions"] = np.clip(np.asarray(data["actions"]),
                                  self.low, self.high)
        return data


class RandomActions(Connector):
    """Uniform random actions — the warmup phase of off-policy continuous
    algorithms (SAC/DDPG learning_starts), run INSTEAD of the module
    forward (reference Random exploration,
    rllib/utils/exploration/random.py)."""

    def __init__(self, action_dim: int, low: float, high: float):
        self.action_dim = action_dim
        self.low = low
        self.high = high

    def __call__(self, data):
        n = len(data["obs"])
        data["actions"] = data["rng"].uniform(
            self.low, self.high, (n, self.action_dim)).astype(np.float32)
        return data
