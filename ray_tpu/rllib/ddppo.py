"""DD-PPO: decentralized distributed PPO.

Reference parity: rllib/algorithms/ddppo/ddppo.py:90,182,261-281 — rollout
workers do their own SGD and synchronize by allreducing *gradients* among
themselves (torch.distributed gloo/nccl there), so no train batch and no
weights ever travel through the driver.

TPU-era translation: each worker pairs a vector env with a jitted local
learner; gradient sync rides `ray_tpu.util.collective` (host backend —
rendezvous actor; the same call sites would compile to XLA psum when the
workers share a mesh). Identical seeds make the initial params equal, and
because every worker applies the same averaged gradient with the same
optimizer, params stay bit-identical without any broadcast — the invariant
the reference relies on too.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import CartPoleEnv, VectorEnv
from ray_tpu.rllib.ppo import (
    compute_gae,
    init_policy_params,
    policy_apply,
)
from ray_tpu.util import collective


@ray_tpu.remote
class _DDPPOWorker:
    """Sampler + local learner, one per rank."""

    def __init__(self, rank: int, world_size: int, group_name: str,
                 env_maker, num_envs: int, seed: int,
                 obs_dim: int, num_actions: int, lr: float, clip: float,
                 vf_coeff: float, entropy_coeff: float):
        import jax
        import optax

        self.rank = rank
        self.world = world_size
        self.group = group_name
        self.vec = VectorEnv(env_maker, num_envs, seed + 1000 * (rank + 1))
        self.obs = self.vec.reset()
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        # identical across ranks: same init seed
        self.params = init_policy_params(seed, obs_dim, num_actions)
        self.optimizer = optax.adam(lr)
        self.opt_state = self.optimizer.init(self.params)
        self.rng = np.random.default_rng(seed + 77 * (rank + 1))
        self._ep_returns = np.zeros(num_envs, np.float32)
        self._completed: List[float] = []

        def loss_fn(params, batch):
            import jax.numpy as jnp

            logits, value = policy_apply(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=-1)[:, 0]
            ratio = jnp.exp(logp - batch["logp"])
            adv = batch["advantages"]
            pg = -jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip, 1 + clip) * adv).mean()
            vf = 0.5 * ((value - batch["returns"]) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg + vf_coeff * vf - entropy_coeff * entropy
            return total, {"policy_loss": pg, "vf_loss": vf,
                           "entropy": entropy}

        self._grad_fn = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))

        def apply_grads(params, opt_state, grads):
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._apply = jax.jit(apply_grads)

    def init_collective(self) -> bool:
        collective.init_collective_group(
            self.world, self.rank, backend="host", group_name=self.group)
        return True

    def _sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        T, N = num_steps, self.vec.num_envs
        bufs = {k: np.zeros((T, N), np.float32)
                for k in ("logp", "values", "rewards", "dones")}
        obs_buf = np.zeros((T, N, self.obs_dim), np.float32)
        act_buf = np.zeros((T, N), np.int32)
        for t in range(T):
            logits, value = policy_apply(self.params, self.obs)
            logits, value = np.asarray(logits), np.asarray(value)
            z = logits - logits.max(-1, keepdims=True)
            probs = np.exp(z) / np.exp(z).sum(-1, keepdims=True)
            actions = np.array(
                [self.rng.choice(self.num_actions, p=p) for p in probs])
            obs_buf[t] = self.obs
            act_buf[t] = actions
            bufs["logp"][t] = np.log(probs[np.arange(N), actions] + 1e-10)
            bufs["values"][t] = value
            self.obs, rewards, dones, _ = self.vec.step(actions)
            bufs["rewards"][t] = rewards
            bufs["dones"][t] = dones
            self._ep_returns += rewards
            for i, d in enumerate(dones):
                if d:
                    self._completed.append(float(self._ep_returns[i]))
                    self._ep_returns[i] = 0.0
        _, last_value = policy_apply(self.params, self.obs)
        return {"obs": obs_buf, "actions": act_buf, **bufs,
                "last_value": np.asarray(last_value)}

    def train_step(self, num_steps: int, gamma: float, lam: float,
                   num_sgd_iter: int, minibatch_size: int) -> Dict[str, Any]:
        import jax

        batch = self._sample(num_steps)
        adv, ret = compute_gae(batch, gamma, lam)
        T, N = batch["actions"].shape
        flat = {
            "obs": batch["obs"].reshape(T * N, -1),
            "actions": batch["actions"].reshape(-1).astype(np.int32),
            "logp": batch["logp"].reshape(-1),
            "advantages": adv.reshape(-1),
            "returns": ret.reshape(-1),
        }
        a = flat["advantages"]
        flat["advantages"] = (a - a.mean()) / (a.std() + 1e-8)

        n = len(flat["obs"])
        stats: Dict[str, Any] = {}
        for _ in range(num_sgd_iter):
            # same permutation seed schedule across ranks is NOT required:
            # each rank trains on its own local minibatches, only the
            # gradient is shared
            idx = self.rng.permutation(n)
            for start in range(0, n, minibatch_size):
                mb = {k: v[idx[start:start + minibatch_size]]
                      for k, v in flat.items()}
                (loss, aux), grads = self._grad_fn(self.params, mb)
                # decentralized sync point (reference ddppo.py:261-281):
                # one fused allreduce over the flattened gradient vector
                leaves, treedef = jax.tree_util.tree_flatten(
                    jax.device_get(grads))
                leaves = [np.asarray(g) for g in leaves]
                sizes = np.cumsum([g.size for g in leaves])[:-1]
                flat_g = np.concatenate([g.ravel() for g in leaves])
                summed = collective.allreduce(flat_g, group_name=self.group)
                parts = np.split(summed / self.world, sizes)
                mean_grads = jax.tree_util.tree_unflatten(treedef, [
                    p.reshape(g.shape).astype(g.dtype)
                    for p, g in zip(parts, leaves)])
                self.params, self.opt_state = self._apply(
                    self.params, self.opt_state, mean_grads)
                stats = {k: float(v)
                         for k, v in jax.device_get(aux).items()}
                stats["total_loss"] = float(loss)
        completed, self._completed = self._completed, []
        return {"episode_returns": completed,
                "num_env_steps": T * N, **stats}

    def get_weights(self) -> Dict[str, np.ndarray]:
        import jax

        return {k: np.asarray(v)
                for k, v in jax.device_get(self.params).items()}

    def set_weights(self, weights) -> bool:
        import jax.numpy as jnp

        self.params = {k: jnp.asarray(v) for k, v in weights.items()}
        self.opt_state = self.optimizer.init(self.params)
        return True


class DDPPOConfig:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: CartPoleEnv(seed)
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.num_workers = 2
        self.num_envs_per_worker = 4
        self.rollout_fragment_length = 64
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.entropy_coeff = 0.01
        self.vf_coeff = 0.5
        self.num_sgd_iter = 2
        self.sgd_minibatch_size = 128
        self.seed = 0

    def environment(self, env_maker=None, *, obs_dim=None,
                    num_actions=None) -> "DDPPOConfig":
        if env_maker is not None:
            self.env_maker = env_maker
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def rollouts(self, *, num_workers=None,
                 num_envs_per_worker=None,
                 rollout_fragment_length=None) -> "DDPPOConfig":
        if num_workers is not None:
            self.num_workers = num_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, *, lr=None, num_sgd_iter=None,
                 sgd_minibatch_size=None) -> "DDPPOConfig":
        for k, v in [("lr", lr), ("num_sgd_iter", num_sgd_iter),
                     ("sgd_minibatch_size", sgd_minibatch_size)]:
            if v is not None:
                setattr(self, k, v)
        return self

    def build(self) -> "DDPPO":
        return DDPPO({"ddppo_config": self})


class DDPPO(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import os
        import uuid

        cfg: DDPPOConfig = config.get("ddppo_config") or DDPPOConfig()
        self.cfg = cfg
        # unique across drivers sharing a cluster — a plain counter would
        # collide when a second driver restarts the sequence
        self._group = f"ddppo-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.workers = [
            _DDPPOWorker.options(num_cpus=1).remote(
                i, cfg.num_workers, self._group, cfg.env_maker,
                cfg.num_envs_per_worker, cfg.seed, cfg.obs_dim,
                cfg.num_actions, cfg.lr, cfg.clip_param, cfg.vf_coeff,
                cfg.entropy_coeff)
            for i in range(cfg.num_workers)
        ]
        ray_tpu.get([w.init_collective.remote() for w in self.workers])
        self._reward_history: List[float] = []
        self._total_steps = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        outs = ray_tpu.get([
            w.train_step.remote(
                cfg.rollout_fragment_length, cfg.gamma, cfg.lambda_,
                cfg.num_sgd_iter, cfg.sgd_minibatch_size)
            for w in self.workers])
        for out in outs:
            self._reward_history.extend(out.pop("episode_returns"))
            self._total_steps += out.pop("num_env_steps")
        self._reward_history = self._reward_history[-100:]
        mean_reward = (float(np.mean(self._reward_history))
                       if self._reward_history else 0.0)
        stats = {k: float(np.mean([o[k] for o in outs])) for k in outs[0]}
        return {"episode_reward_mean": mean_reward,
                "num_env_steps_sampled": self._total_steps, **stats}

    def get_weights(self):
        return ray_tpu.get(self.workers[0].get_weights.remote())

    def set_weights(self, weights) -> None:
        ray_tpu.get([w.set_weights.remote(weights) for w in self.workers])

    def stop(self) -> None:
        self._kill_workers(self.workers)
        # the rendezvous actor was created inside rank 0's process, so the
        # driver-side registry doesn't know it — kill it by name
        try:
            ray_tpu.kill(ray_tpu.get_actor(f"_collective:{self._group}"))
        except (ValueError, KeyError, ConnectionError):
            pass  # group actor already gone (normal teardown order)
