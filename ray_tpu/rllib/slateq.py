"""SlateQ: Q-learning for slate recommendation (Ie et al. 2019).

Reference parity: rllib/algorithms/slateq/ (SURVEY §2.3 algorithm list).
The environment is a compact interest-evolution recommender (the RecSim
family the reference trains against): the user has a latent topic-interest
vector, the agent slates K of N candidate docs, the user clicks via a
conditional choice model (softmax over interest·doc, with a no-click
option) and clicked docs pay their engagement quality and drift the
user's interests.

SlateQ's decomposition: the slate's Q-value is the choice-probability-
weighted sum of per-item Q(s, d) — learning stays item-level (tractable)
while acting optimizes over slates (greedy top-K by choice-score-weighted
Q, the standard LP-relaxation shortcut). TD backup bootstraps the next
state's greedy slate value. All updates are jitted JAX.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.models import init_mlp, mlp_forward
from ray_tpu.rllib.replay_buffers import ReplayBuffer


class InterestEvolutionEnv:
    """1-step-per-slate recommender: obs = (user interests, candidate doc
    features); action = K-doc slate (index tuple)."""

    def __init__(self, seed: int = 0, n_topics: int = 4,
                 n_candidates: int = 10, slate_size: int = 3,
                 episode_len: int = 20, no_click_mass: float = 1.0,
                 drift: float = 0.2):
        self.rng = np.random.default_rng(seed)
        self.n_topics = n_topics
        self.n_candidates = n_candidates
        self.slate_size = slate_size
        self.episode_len = episode_len
        self.no_click_mass = no_click_mass
        self.drift = drift

    def _sample_docs(self) -> np.ndarray:
        """[N, T+1]: one-hot-ish topic mix + quality scalar."""
        topics = self.rng.dirichlet(np.ones(self.n_topics) * 0.3,
                                    self.n_candidates)
        quality = self.rng.uniform(0, 1, (self.n_candidates, 1))
        return np.concatenate([topics, quality], axis=1).astype(np.float32)

    def _obs(self) -> Dict[str, np.ndarray]:
        return {"user": self.user.copy(), "docs": self.docs.copy()}

    def reset(self) -> Dict[str, np.ndarray]:
        self.user = self.rng.dirichlet(
            np.ones(self.n_topics)).astype(np.float32)
        self.docs = self._sample_docs()
        self.t = 0
        return self._obs()

    def choice_probs(self, slate: Tuple[int, ...]) -> np.ndarray:
        """User's conditional choice over slate items + no-click (last)."""
        scores = np.array([
            float(self.user @ self.docs[d, :self.n_topics])
            for d in slate] + [0.0])
        scores[-1] = np.log(self.no_click_mass + 1e-9)
        z = np.exp(scores - scores.max())
        return z / z.sum()

    def step(self, slate: Tuple[int, ...]):
        probs = self.choice_probs(slate)
        pick = self.rng.choice(len(probs), p=probs)
        reward = 0.0
        clicked_doc = -1
        if pick < len(slate):  # clicked item `pick`
            d = slate[pick]
            clicked_doc = int(d)
            reward = float(self.docs[d, -1])  # engagement = quality
            topic = self.docs[d, :self.n_topics]
            self.user = (1 - self.drift) * self.user + self.drift * topic
            self.user = (self.user / self.user.sum()).astype(np.float32)
        self.t += 1
        done = self.t >= self.episode_len
        self.docs = self._sample_docs()
        return self._obs(), reward, done, {
            "clicked": pick < len(slate), "doc": clicked_doc}


class SlateQConfig:
    def __init__(self):
        self.n_topics = 4
        self.n_candidates = 10
        self.slate_size = 3
        self.lr = 1e-3
        self.gamma = 0.95
        self.epsilon = 0.15
        self.buffer_size = 50_000
        self.batch_size = 128
        self.warmup_steps = 300
        self.target_update_freq = 100
        self.episodes_per_iter = 10
        self.updates_per_iter = 60
        self.seed = 0
        self.env_maker = None  # default InterestEvolutionEnv

    def training(self, **kw) -> "SlateQConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown option {k!r}")
            if v is not None:
                setattr(self, k, v)
        return self

    def build(self) -> "SlateQ":
        return SlateQ({"slateq_config": self})


class SlateQ(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        cfg: SlateQConfig = config.get("slateq_config") or SlateQConfig()
        self.cfg = cfg
        self.env = (cfg.env_maker(cfg.seed) if cfg.env_maker
                    else InterestEvolutionEnv(
                        cfg.seed, cfg.n_topics, cfg.n_candidates,
                        cfg.slate_size))
        rng = np.random.default_rng(cfg.seed)
        # item Q-network: input = [user(T), doc(T+1)]
        in_dim = cfg.n_topics + cfg.n_topics + 1
        self.params = init_mlp(rng, (in_dim, 64, 64, 1),
                               final_scale=np.sqrt(2.0 / 64))
        self.target_params = {k: v.copy() for k, v in self.params.items()}
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self.rng = rng
        self._total_steps = 0
        self._update_count = 0
        self._reward_history: List[float] = []

        T, K = cfg.n_topics, cfg.slate_size
        no_click = np.log(self.env.no_click_mass + 1e-9)
        gamma = cfg.gamma

        def item_q(params, user, docs):
            # user [B,T], docs [B,N,T+1] -> [B,N]
            B, N, _ = docs.shape
            u = jnp.broadcast_to(user[:, None, :], (B, N, T))
            x = jnp.concatenate([u, docs], axis=-1)
            return mlp_forward(params, x, 3)[..., 0]

        def greedy_slate_value(params, user, docs):
            """max_slate sum_i P(i|slate) Q(i): rank by score-weighted Q
            (LP-relaxation shortcut), evaluate the chosen top-K slate under
            the true conditional-choice softmax."""
            q = item_q(params, user, docs)  # [B,N]
            scores = jnp.einsum("bt,bnt->bn", user, docs[..., :T])
            w = jnp.exp(scores)
            ranked = jnp.argsort(-(w * jnp.maximum(q, 0.0) + 1e-9 * q),
                                 axis=-1)[:, :K]
            top_scores = jnp.take_along_axis(scores, ranked, axis=1)
            top_q = jnp.take_along_axis(q, ranked, axis=1)
            z = jnp.concatenate(
                [jnp.exp(top_scores),
                 jnp.full((user.shape[0], 1), np.exp(no_click))], axis=1)
            probs = z / z.sum(axis=1, keepdims=True)
            return (probs[:, :K] * top_q).sum(axis=1)

        self._item_q = jax.jit(item_q)
        self._greedy_value = jax.jit(greedy_slate_value)

        def loss_fn(params, target_params, batch):
            # Q(s, clicked_doc) towards r + gamma * V_greedy(s')
            q_all = item_q(params, batch["user"], batch["docs"])
            q_taken = jnp.take_along_axis(
                q_all, batch["doc_idx"][:, None].astype(jnp.int32),
                axis=1)[:, 0]
            v_next = greedy_slate_value(
                target_params, batch["next_user"], batch["next_docs"])
            backup = jax.lax.stop_gradient(
                batch["rewards"] + gamma * (1 - batch["dones"]) * v_next)
            return ((q_taken - backup) ** 2).mean()

        def update(params, opt_state, target_params, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, target_params, batch)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update = jax.jit(update)

    # ------------------------------------------------------------- acting
    def _select_slate(self, obs: Dict[str, np.ndarray],
                      epsilon: float) -> Tuple[int, ...]:
        cfg = self.cfg
        if self.rng.random() < epsilon:
            return tuple(self.rng.choice(
                cfg.n_candidates, cfg.slate_size, replace=False))
        q = np.asarray(self._item_q(
            self.params, obs["user"][None], obs["docs"][None]))[0]
        scores = obs["docs"][:, :cfg.n_topics] @ obs["user"]
        rank = np.argsort(-(np.exp(scores) * np.maximum(q, 0.0) + 1e-9 * q))
        return tuple(int(i) for i in rank[:cfg.slate_size])

    def _run_episode(self, epsilon: float, store: bool = True) -> float:
        env = self.env
        obs = env.reset()
        total = 0.0
        while True:
            slate = self._select_slate(obs, epsilon)
            nxt, reward, done, info = env.step(slate)
            total += reward
            if store:
                # item-level SARSA on CLICKED items only (the paper's
                # update — no-click steps carry no item-level signal)
                if info["clicked"]:
                    self.buffer.add_batch({
                        "user": obs["user"][None],
                        "docs": obs["docs"][None],
                        "doc_idx": np.array([info["doc"]], np.int32),
                        "rewards": np.array([reward], np.float32),
                        "next_user": nxt["user"][None],
                        "next_docs": nxt["docs"][None],
                        "dones": np.array([float(done)], np.float32),
                    })
                self._total_steps += 1
            obs = nxt
            if done:
                return total

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        cfg = self.cfg
        returns = [self._run_episode(cfg.epsilon)
                   for _ in range(cfg.episodes_per_iter)]
        loss = float("nan")
        if self._total_steps >= cfg.warmup_steps:
            for _ in range(cfg.updates_per_iter):
                batch = self.buffer.sample(cfg.batch_size)
                batch = {k: jnp.asarray(v) for k, v in batch.items()}
                self.params, self.opt_state, l = self._update(
                    self.params, self.opt_state, self.target_params, batch)
                loss = float(l)
                self._update_count += 1
                if self._update_count % cfg.target_update_freq == 0:
                    self.target_params = {
                        k: np.asarray(v).copy()
                        for k, v in self.params.items()}
        self._reward_history.extend(returns)
        self._reward_history = self._reward_history[-100:]
        return {"episode_reward_mean": float(np.mean(self._reward_history)),
                "num_env_steps_sampled": self._total_steps,
                "td_loss": loss}

    def greedy_return(self, episodes: int = 10) -> float:
        return float(np.mean([self._run_episode(0.0, store=False)
                              for _ in range(episodes)]))

    def random_baseline(self, episodes: int = 10) -> float:
        return float(np.mean([self._run_episode(1.0, store=False)
                              for _ in range(episodes)]))

    def get_weights(self):
        return {"params": {k: np.asarray(v)
                           for k, v in self.params.items()},
                "target": {k: np.asarray(v)
                           for k, v in self.target_params.items()}}

    def set_weights(self, weights) -> None:
        self.params = dict(weights["params"])
        self.target_params = dict(weights["target"])
