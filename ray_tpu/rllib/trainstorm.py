"""TRAINSTORM: the RL fleet under composed chaos, with a committed artifact.

`python -m ray_tpu.rllib.trainstorm` runs the rollout->learner loop
(rllib/fleet.py) while three seeded failure modes fire mid-training:

  1. **replica kills** — a killer thread hard-kills live rollout replicas on
     a period; mid-episode requests recover via serve mid-request failover
     and the controller restarts replacements (which pick the latest weight
     epoch up from the recorded user_config).
  2. **learner crash-restart** — the named learner actor is killed once;
     the driver recreates it, it restores from the latest *complete*
     checkpoint, and exactly-once ingest accounting (rollout-id dedupe in
     the checkpoint) guarantees no batch is applied twice across the
     restart. Recovery is measured kill -> first post-restart applied step.
  3. **partition-heal** — a `partition:learner|replicas` blackhole severs
     the fleet_ingest/fleet_weights boundaries for a window, then heals;
     the driver's bounded retry loops must converge with zero hung futures.

The run commits `TRAINSTORM_r17.json`: samples/s, learner steps/s,
recovery-to-first-post-restart-step, the staleness histogram, chaos event
counts and `zero_hung`. CI replays a `--quick` profile and asserts on the
required rows; `tests/test_envelope.py` floors the two rates against
machine-calibrated probes.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os as _os
import random
import shutil
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

DEFAULT_ARTIFACT = "TRAINSTORM_r17.json"
ROUND = 17


@dataclasses.dataclass
class TrainStormProfile:
    duration_s: float = 30.0
    seed: int = 0
    # fleet shape (forwarded into FleetConfig; env RAY_TPU_FLEET_* still
    # overrides anything not set here)
    num_replicas: int = 3
    num_envs: int = 2
    rollout_len: int = 32
    max_staleness: int = 2
    checkpoint_every: int = 3
    keep_checkpoints: int = 3
    broadcast_every: int = 1
    policy: str = "mlp"
    # chaos schedule
    replica_kill_period_s: float = 6.0
    learner_kill_at_frac: float = 0.35   # one crash-restart mid-run
    partition_at_frac: float = 0.6
    partition_duration_s: float = 4.0
    # budgets
    recovery_budget_s: float = 30.0
    drain_grace_s: float = 60.0
    # loop timeouts (forwarded into FleetConfig)
    sample_timeout_s: float = 60.0
    ingest_timeout_s: float = 15.0
    ingest_deadline_s: float = 45.0


QUICK_PROFILE = dict(duration_s=12.0, replica_kill_period_s=4.0,
                     rollout_len=16, checkpoint_every=2,
                     partition_duration_s=2.5, num_replicas=2,
                     sample_timeout_s=30.0, ingest_timeout_s=10.0,
                     ingest_deadline_s=25.0, drain_grace_s=45.0,
                     recovery_budget_s=45.0)


def _effective_cpus() -> int:
    try:
        return len(_os.sched_getaffinity(0)) or 1
    except (AttributeError, OSError):
        return _os.cpu_count() or 1


def run_trainstorm(profile: Optional[TrainStormProfile] = None,
                   out_path: Optional[str] = DEFAULT_ARTIFACT,
                   ckpt_root: Optional[str] = None) -> Dict[str, Any]:
    """Run one storm on the CURRENT cluster (caller already init'd).
    Returns the result dict (written to out_path unless None). Never raises
    on a dirty run — callers assert on result["violations"]."""
    import ray_tpu
    from ray_tpu.core import rpc as _rpc
    from ray_tpu.rllib.fleet import (LEARNER_ACTOR_NAME, LEARNER_GROUP,
                                     REPLICA_GROUP, FleetConfig, FleetDriver,
                                     define_fleet_groups)

    p = profile or TrainStormProfile()
    rng = random.Random(p.seed)
    cfg = FleetConfig.from_env(
        num_replicas=p.num_replicas, num_envs=p.num_envs,
        rollout_len=p.rollout_len, max_staleness=p.max_staleness,
        checkpoint_every=p.checkpoint_every,
        keep_checkpoints=p.keep_checkpoints,
        broadcast_every=p.broadcast_every, policy=p.policy, seed=p.seed,
        sample_timeout_s=p.sample_timeout_s,
        ingest_timeout_s=p.ingest_timeout_s,
        ingest_deadline_s=p.ingest_deadline_s)
    owns_ckpt = ckpt_root is None
    ckpt_root = ckpt_root or tempfile.mkdtemp(prefix="trainstorm_ckpt_")
    # an injector with no spec rules: partitions are armed at runtime so
    # the blackhole window is scheduled, not probabilistic
    injector = _rpc.install_fault_injector("", p.seed)
    define_fleet_groups(injector)

    driver = FleetDriver(cfg, ckpt_root)
    t_start = time.monotonic()
    try:
        driver.start()
        return _run_inner(p, rng, cfg, driver, injector, out_path, t_start)
    finally:
        try:
            driver.stop()
        finally:
            _rpc.clear_fault_injector()
            if owns_ckpt:
                shutil.rmtree(ckpt_root, ignore_errors=True)


def _run_inner(p: TrainStormProfile, rng: random.Random, cfg, driver,
               injector, out_path: Optional[str],
               t_start: float) -> Dict[str, Any]:
    import ray_tpu
    from ray_tpu.rllib.fleet import LEARNER_ACTOR_NAME

    stop = threading.Event()
    rounds = 0
    env_steps_applied = 0
    loop_error: List[BaseException] = []

    def loop() -> None:
        nonlocal rounds, env_steps_applied
        while not stop.is_set():
            try:
                m = driver.train_round()
            except BaseException as e:  # a storm must surface, not die
                loop_error.append(e)
                logger.warning("train loop error", exc_info=True)
                time.sleep(0.2)
                continue
            rounds += 1
            env_steps_applied += m["applied_env_steps"]

    replica_kills = 0

    def replica_killer() -> None:
        nonlocal replica_kills
        while not stop.wait(p.replica_kill_period_s):
            try:
                handle = driver._handle
                with handle._lock:
                    replicas = list(handle._replicas)
                if len(replicas) < 2:
                    continue  # never kill the last replica
                victim = replicas[rng.randrange(len(replicas))]
                ray_tpu.kill(victim)
                replica_kills += 1
                logger.info("trainstorm killed a rollout replica")
            except Exception:
                logger.warning("replica kill pass failed", exc_info=True)

    learner_kill: Dict[str, Any] = {"kills": 0, "recovery_s": None,
                                    "applied_at_kill": None,
                                    "step_at_kill": None}

    def learner_killer() -> None:
        if stop.wait(p.duration_s * p.learner_kill_at_frac):
            return
        try:
            info = driver.learner_info(timeout=30)
            victim = ray_tpu.get_actor(LEARNER_ACTOR_NAME)
            learner_kill["applied_at_kill"] = driver.outcomes.applied
            learner_kill["step_at_kill"] = info["step"]
            t_kill = time.monotonic()
            ray_tpu.kill(victim, no_restart=True)
            learner_kill["kills"] += 1
            logger.info("trainstorm killed the learner at step %d",
                        info["step"])
            # recovery = kill -> first post-restart APPLIED step; keep
            # watching through the drain window (a slow box often lands
            # the post-restart step after the storm clock stops)
            watch_until = t_kill + p.recovery_budget_s + p.drain_grace_s
            while time.monotonic() < watch_until:
                if driver.outcomes.applied > learner_kill["applied_at_kill"]:
                    learner_kill["recovery_s"] = time.monotonic() - t_kill
                    return
                time.sleep(0.05)
        except Exception:
            logger.warning("learner kill failed", exc_info=True)

    partition: Dict[str, Any] = {"injected": 0, "healed": 0,
                                 "window_s": p.partition_duration_s,
                                 "retries_during": 0}

    def partitioner() -> None:
        from ray_tpu.rllib.fleet import LEARNER_GROUP, REPLICA_GROUP

        if stop.wait(p.duration_s * p.partition_at_frac):
            return
        retries_before = driver.outcomes.retries
        injector.partition(LEARNER_GROUP, REPLICA_GROUP)
        partition["injected"] += 1
        logger.info("trainstorm partitioned learner|replicas")
        stop.wait(p.partition_duration_s)
        partition["healed"] += injector.heal()
        partition["retries_during"] = (driver.outcomes.retries
                                       - retries_before)
        logger.info("trainstorm healed the partition")

    threads = [threading.Thread(target=f, daemon=True, name=n)
               for f, n in ((loop, "ts-loop"),
                            (replica_killer, "ts-replica-killer"),
                            (learner_killer, "ts-learner-killer"),
                            (partitioner, "ts-partitioner"))]
    for t in threads:
        t.start()
    time.sleep(p.duration_s)
    stop.set()
    window_s = time.monotonic() - t_start
    applied_at_stop = driver.outcomes.applied
    env_steps_at_stop = env_steps_applied
    driver.stop_event.set()  # abort in-flight retry loops cooperatively

    # Drain: every thread must exit inside the grace window — a stuck loop
    # IS a hung future (an unresolved get inside train_round).
    hung = 0
    for t in threads:
        t.join(timeout=p.drain_grace_s)
        if t.is_alive():
            hung += 1
            logger.error("trainstorm thread %s failed to drain", t.name)
    elapsed = time.monotonic() - t_start

    info: Dict[str, Any] = {}
    fence_stats: List[dict] = []
    try:
        info = driver.learner_info(timeout=60)
        fence_stats = driver.fence_stats(timeout=30)
    except Exception:
        hung += 1
        logger.error("post-storm learner_info unresolved", exc_info=True)

    # Rates over the ACTIVE window (chaos included, drain excluded):
    # samples/s = env transitions ingested+applied; learner steps/s =
    # batches applied (one optimizer pass each).
    samples_per_s = env_steps_at_stop / window_s if window_s > 0 else 0.0
    steps_per_s = applied_at_stop / window_s if window_s > 0 else 0.0

    violations: List[str] = []
    if hung:
        violations.append(f"hung: {hung} unresolved thread(s)/future(s)")
    if loop_error:
        violations.append(f"loop_error: {loop_error[0]!r}")
    if replica_kills < 1:
        violations.append("chaos: no replica kill landed")
    if learner_kill["kills"] < 1:
        violations.append("chaos: no learner crash-restart landed")
    if partition["injected"] < 1 or partition["healed"] < 1:
        violations.append("chaos: no partition-heal cycle landed")
    if learner_kill["kills"] and learner_kill["recovery_s"] is None:
        violations.append("recovery: no post-restart step before drain")
    elif (learner_kill["recovery_s"] is not None
          and learner_kill["recovery_s"] > p.recovery_budget_s):
        violations.append(
            f"recovery: {learner_kill['recovery_s']:.1f}s > "
            f"budget {p.recovery_budget_s:.1f}s")
    if driver.outcomes.applied < 1:
        violations.append("liveness: no batch applied at all")

    result: Dict[str, Any] = {
        "bench": "trainstorm",
        "round": ROUND,
        "seed": p.seed,
        "policy": cfg.policy,
        "effective_cpus": _effective_cpus(),
        "duration_s": round(elapsed, 3),
        "profile": dataclasses.asdict(p),
        "rounds": rounds,
        "samples_per_s": round(samples_per_s, 3),
        "learner_steps_per_s": round(steps_per_s, 3),
        "learner_steps": info.get("step", 0),
        "applied_batches": driver.outcomes.applied,
        "duplicate_batches": driver.outcomes.duplicate,
        "stale_batches": driver.outcomes.stale,
        "partition_dropped_batches": driver.outcomes.partition_dropped,
        "ingest_retries": driver.outcomes.retries,
        "staleness_hist": {str(k): v for k, v in sorted(
            driver.staleness_hist.items())},
        "staleness_hist_since_restart": {str(k): v for k, v in sorted(
            (info.get("staleness_hist") or {}).items())},
        "weight_epoch": info.get("epoch", 0),
        "broadcasts": driver.broadcasts,
        "broadcast_failures": driver.broadcast_failures,
        "fenced_updates": sum(s.get("fenced", 0) for s in fence_stats),
        "replica_kills": replica_kills,
        "learner_kills": learner_kill["kills"],
        "learner_restarts": driver.learner_restarts,
        "learner_step_at_kill": learner_kill["step_at_kill"],
        "recovery_to_first_post_restart_step_s": (
            None if learner_kill["recovery_s"] is None
            else round(learner_kill["recovery_s"], 3)),
        "recovery_budget_s": p.recovery_budget_s,
        "partition": partition,
        "sample_failures": driver.sample_failures,
        "zero_hung": hung == 0,
        "violations": violations,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
            f.write("\n")
    return result


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    import ray_tpu

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--seed", type=int, default=None,
                    help="chaos-schedule + fleet seed (default: "
                         "RAY_TPU_FAULT_INJECTION_SEED or 0)")
    ap.add_argument("--quick", action="store_true",
                    help="short CI profile (~12 s, 2 replicas)")
    ap.add_argument("--policy", choices=("mlp", "transformer"),
                    default="mlp")
    ap.add_argument("--json", default=DEFAULT_ARTIFACT,
                    help=f"artifact path (default {DEFAULT_ARTIFACT})")
    args = ap.parse_args(argv)

    seed = (args.seed if args.seed is not None
            else int(_os.environ.get("RAY_TPU_FAULT_INJECTION_SEED", "0")))
    kw: Dict[str, Any] = dict(seed=seed, duration_s=args.duration,
                              policy=args.policy)
    if args.quick:
        kw.update(QUICK_PROFILE)
    profile = TrainStormProfile(**kw)

    ray_tpu.init(num_cpus=max(8, profile.num_replicas + 4),
                 resources={"TPU": 8})
    try:
        result = run_trainstorm(profile, out_path=args.json)
    finally:
        try:
            from ray_tpu import serve

            serve.shutdown()
        finally:
            ray_tpu.shutdown()

    print(f"trainstorm[r{ROUND}] seed={result['seed']} "
          f"policy={result['policy']} {result['duration_s']:.1f}s on "
          f"{result['effective_cpus']} effective cpus")
    print(f"  samples/s={result['samples_per_s']:.1f} "
          f"learner_steps/s={result['learner_steps_per_s']:.2f} "
          f"steps={result['learner_steps']} epoch={result['weight_epoch']}")
    print(f"  chaos: replica_kills={result['replica_kills']} "
          f"learner_kills={result['learner_kills']} "
          f"partition={result['partition']['injected']}/"
          f"{result['partition']['healed']} "
          f"recovery={result['recovery_to_first_post_restart_step_s']}s")
    print(f"  accounting: applied={result['applied_batches']} "
          f"dup={result['duplicate_batches']} stale={result['stale_batches']} "
          f"fenced={result['fenced_updates']} "
          f"staleness_hist={result['staleness_hist']}")
    print(f"  zero_hung={result['zero_hung']}")
    if result["violations"]:
        for v in result["violations"]:
            print(f"  VIOLATION: {v}")
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
