"""Environments: a dependency-free CartPole + vectorization.

The reference wraps gym (`rllib/env/vector_env.py`); this build ships a
numpy CartPole (classic Barto-Sutton dynamics, the same the reference's CI
learning tests train on) so the RL stack is testable with zero external
env deps. Any object with reset()->obs / step(a)->(obs, r, done, info)
works as an env.
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import numpy as np


class CartPoleEnv:
    """CartPole-v1 dynamics (max 500 steps, solved ~475)."""

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    observation_dim = 4
    num_actions = 2

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._state = None
        self._steps = 0

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._steps = 0
        return self._state.astype(np.float32)

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, dict]:
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.CART_MASS + self.POLE_MASS
        polemass_length = self.POLE_MASS * self.POLE_HALF_LEN
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        theta_acc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.POLE_HALF_LEN * (4.0 / 3.0 - self.POLE_MASS * costheta**2 / total_mass))
        x_acc = temp - polemass_length * theta_acc * costheta / total_mass
        x += self.DT * x_dot
        x_dot += self.DT * x_acc
        theta += self.DT * theta_dot
        theta_dot += self.DT * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._steps += 1
        done = bool(
            abs(x) > self.X_LIMIT or abs(theta) > self.THETA_LIMIT
            or self._steps >= self.MAX_STEPS)
        return self._state.astype(np.float32), 1.0, done, {}


class VectorEnv:
    """N independent env copies stepped together (reference vector_env.py)."""

    def __init__(self, env_fn: Callable[[int], Any], num_envs: int, seed: int = 0):
        self.envs = [env_fn(seed + i) for i in range(num_envs)]
        self.num_envs = num_envs

    def reset(self) -> np.ndarray:
        return np.stack([e.reset() for e in self.envs])

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[dict]]:
        obs, rews, dones, infos = [], [], [], []
        for e, a in zip(self.envs, actions):
            o, r, d, i = e.step(int(a))
            if d:
                o = e.reset()
            obs.append(o)
            rews.append(r)
            dones.append(d)
            infos.append(i)
        return np.stack(obs), np.array(rews, np.float32), np.array(dones), infos
