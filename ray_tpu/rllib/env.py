"""Environments: a dependency-free CartPole + vectorization.

The reference wraps gym (`rllib/env/vector_env.py`); this build ships a
numpy CartPole (classic Barto-Sutton dynamics, the same the reference's CI
learning tests train on) so the RL stack is testable with zero external
env deps. Any object with reset()->obs / step(a)->(obs, r, done, info)
works as an env.
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import numpy as np


class CartPoleEnv:
    """CartPole-v1 dynamics (max 500 steps, solved ~475)."""

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    observation_dim = 4
    num_actions = 2

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._state = None
        self._steps = 0

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._steps = 0
        return self._state.astype(np.float32)

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, dict]:
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE if action == 1 else -self.FORCE
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.CART_MASS + self.POLE_MASS
        polemass_length = self.POLE_MASS * self.POLE_HALF_LEN
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        theta_acc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.POLE_HALF_LEN * (4.0 / 3.0 - self.POLE_MASS * costheta**2 / total_mass))
        x_acc = temp - polemass_length * theta_acc * costheta / total_mass
        x += self.DT * x_dot
        x_dot += self.DT * x_acc
        theta += self.DT * theta_dot
        theta_dot += self.DT * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._steps += 1
        done = bool(
            abs(x) > self.X_LIMIT or abs(theta) > self.THETA_LIMIT
            or self._steps >= self.MAX_STEPS)
        return self._state.astype(np.float32), 1.0, done, {}


class PendulumEnv:
    """Pendulum-v1 dynamics: continuous torque control, reward in [-16.27, 0].

    The continuous-control counterpart to CartPole for SAC/DDPG/TD3 learning
    tests (the reference trains these on gym Pendulum in
    rllib/tuned_examples/sac, ddpg).
    """

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0
    MAX_STEPS = 200

    observation_dim = 3
    action_dim = 1
    max_action = MAX_TORQUE

    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)
        self._theta = 0.0
        self._theta_dot = 0.0
        self._steps = 0

    def _obs(self) -> np.ndarray:
        return np.array(
            [np.cos(self._theta), np.sin(self._theta), self._theta_dot],
            np.float32)

    def reset(self) -> np.ndarray:
        self._theta = self._rng.uniform(-np.pi, np.pi)
        self._theta_dot = self._rng.uniform(-1.0, 1.0)
        self._steps = 0
        return self._obs()

    def step(self, action) -> Tuple[np.ndarray, float, bool, dict]:
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          -self.MAX_TORQUE, self.MAX_TORQUE))
        th, thdot = self._theta, self._theta_dot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th**2 + 0.1 * thdot**2 + 0.001 * u**2
        thdot = thdot + (
            3 * self.G / (2 * self.L) * np.sin(th)
            + 3.0 / (self.M * self.L**2) * u) * self.DT
        thdot = float(np.clip(thdot, -self.MAX_SPEED, self.MAX_SPEED))
        th = th + thdot * self.DT
        self._theta, self._theta_dot = th, thdot
        self._steps += 1
        done = self._steps >= self.MAX_STEPS
        return self._obs(), -float(cost), done, {}


class VectorEnv:
    """N independent env copies stepped together (reference vector_env.py).

    `discrete` controls the per-env action cast: int for discrete envs,
    pass-through arrays for continuous ones.
    """

    def __init__(self, env_fn: Callable[[int], Any], num_envs: int,
                 seed: int = 0, discrete: bool = True):
        self.envs = [env_fn(seed + i) for i in range(num_envs)]
        self.env_maker = env_fn  # evaluation spins fresh envs from this
        self.num_envs = num_envs
        self._cast = int if discrete else (lambda a: a)

    def reset(self) -> np.ndarray:
        return np.stack([e.reset() for e in self.envs])

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[dict]]:
        obs, rews, dones, infos = [], [], [], []
        for e, a in zip(self.envs, actions):
            o, r, d, i = e.step(self._cast(a))
            if d:
                o = e.reset()
            obs.append(o)
            rews.append(r)
            dones.append(d)
            infos.append(i)
        return np.stack(obs), np.array(rews, np.float32), np.array(dones), infos


class ContinuousVectorEnv(VectorEnv):
    """VectorEnv without the int() action cast, for continuous control."""

    def __init__(self, env_fn: Callable[[int], Any], num_envs: int, seed: int = 0):
        super().__init__(env_fn, num_envs, seed, discrete=False)
