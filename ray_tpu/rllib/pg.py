"""PG: vanilla policy gradient (REINFORCE).

Mirrors the reference's PG (`rllib/algorithms/pg/pg.py`,
`pg_tf_policy.py`: loss = -mean(logp * returns), no critic, no GAE — the
minimal on-policy baseline): one parallel sample round, Monte-Carlo
reward-to-go returns, a single policy-gradient step on the Learner stack.
Reuses the PPO rollout fleet (module + connector acting); the value head
of the shared module is simply untrained.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import CartPoleEnv
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.ppo import RolloutWorker, compute_gae


class PGLearner(Learner):
    """-mean(logp * returns) with an entropy bonus; critic-free
    (reference pg_tf_policy.py `pg_tf_loss`)."""

    def __init__(self, obs_dim: int, num_actions: int, lr: float,
                 entropy_coeff: float = 0.0, seed: int = 0, mesh=None,
                 module=None):
        from ray_tpu.rllib.rl_module import DiscreteActorCriticModule

        self.module = module or DiscreteActorCriticModule(obs_dim, num_actions)
        self._entropy_coeff = entropy_coeff
        super().__init__(lr=lr, mesh=mesh, seed=seed)

    def init_params(self, seed: int):
        return self.module.init_params(seed)

    def loss(self, params, batch, extra, rng):
        out = self.module.forward_train(params, batch)
        dist = self.module.action_dist(out)
        logp = dist.logp(batch["actions"])
        pg = -(logp * batch["returns"]).mean()
        entropy = dist.entropy().mean()
        total = pg - self._entropy_coeff * entropy
        return total, {"policy_loss": pg, "entropy": entropy}

    def update_once(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax

        aux = self.update(batch)
        return {k: float(v) for k, v in jax.device_get(aux).items()}


class PGConfig:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: CartPoleEnv(seed)
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 4
        self.rollout_fragment_length = 64
        self.lr = 5e-3
        self.gamma = 0.99
        self.entropy_coeff = 0.0
        self.seed = 0

    def environment(self, env_maker=None, *, obs_dim=None, num_actions=None):
        if env_maker is not None:
            self.env_maker = env_maker
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def rollouts(self, *, num_rollout_workers=None, num_envs_per_worker=None,
                 rollout_fragment_length=None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown PG option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "PG":
        return PG({"pg_config": self})


class PG(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        cfg: PGConfig = config.get("pg_config") or PGConfig()
        self.cfg = cfg
        self.learner = PGLearner(cfg.obs_dim, cfg.num_actions, cfg.lr,
                                 cfg.entropy_coeff, cfg.seed)
        self.workers = [
            RolloutWorker.options(num_cpus=1).remote(
                cfg.env_maker, cfg.num_envs_per_worker,
                cfg.seed + 1000 * (i + 1), cfg.obs_dim, cfg.num_actions)
            for i in range(cfg.num_rollout_workers)]
        self._broadcast_weights()
        self._reward_history: List[float] = []
        self._total_steps = 0

    def _broadcast_weights(self) -> None:
        from ray_tpu.rllib.learner import broadcast_weights

        broadcast_weights(self.learner.get_weights(), self.workers)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        samples = ray_tpu.get([
            wk.sample.remote(cfg.rollout_fragment_length)
            for wk in self.workers])
        flats, episode_returns = [], []
        for batch in samples:
            # Monte-Carlo reward-to-go = GAE with a zero critic and
            # lambda=1 (no bootstrap beyond the fragment tail)
            zeroed = dict(batch, values=np.zeros_like(batch["values"]),
                          last_value=np.zeros_like(batch["last_value"]))
            ret, _ = compute_gae(zeroed, cfg.gamma, 1.0)
            T, N = batch["actions"].shape
            flats.append({
                "obs": batch["obs"].reshape(T * N, -1),
                "actions": batch["actions"].reshape(-1),
                "returns": ret.reshape(-1),
            })
            episode_returns.extend(batch["episode_returns"].tolist())
        flat = {k: np.concatenate([f[k] for f in flats]) for k in flats[0]}
        ret = flat["returns"]
        flat["returns"] = (ret - ret.mean()) / (ret.std() + 1e-8)
        self._total_steps += int(flat["actions"].size)
        stats = self.learner.update_once(flat)
        self._broadcast_weights()
        if episode_returns:
            self._reward_history.extend(episode_returns)
            self._reward_history = self._reward_history[-100:]
        return {
            "episode_reward_mean": (float(np.mean(self._reward_history))
                                    if self._reward_history else 0.0),
            "num_env_steps_sampled": self._total_steps,
            **stats,
        }

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        self.learner.set_weights(weights)
        self._broadcast_weights()

    def stop(self) -> None:
        self._kill_workers(self.workers)
