"""RandomAgent: the uniform-random baseline.

Mirrors the reference's RandomAgent (`rllib/algorithms/random_agent.py`):
acts uniformly at random, reports episode-reward statistics — the sanity
floor every learning curve is compared against. Rides the same module +
connector contract as real algorithms (RandomActions is the whole
module-to-env pipeline).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import CartPoleEnv, VectorEnv


class RandomAgentConfig:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: CartPoleEnv(seed)
        self.num_actions = CartPoleEnv.num_actions
        self.num_envs = 4
        self.rollouts_per_iter = 64
        self.seed = 0

    def environment(self, env_maker=None, *, num_actions=None):
        if env_maker is not None:
            self.env_maker = env_maker
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown RandomAgent option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "RandomAgent":
        return RandomAgent({"random_agent_config": self})


class RandomAgent(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        cfg = config.get("random_agent_config") or RandomAgentConfig()
        self.cfg = cfg
        self.vec = VectorEnv(cfg.env_maker, cfg.num_envs, cfg.seed)
        self.obs = self.vec.reset()
        self._rng = np.random.default_rng(cfg.seed)
        self._ep_returns = np.zeros(cfg.num_envs, np.float32)
        self._reward_history: List[float] = []
        self._total_steps = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        for _ in range(cfg.rollouts_per_iter):
            actions = self._rng.integers(0, cfg.num_actions, cfg.num_envs)
            self.obs, rewards, dones, _ = self.vec.step(actions)
            self._total_steps += cfg.num_envs
            self._ep_returns += rewards
            for i, d in enumerate(dones):
                if d:
                    self._reward_history.append(float(self._ep_returns[i]))
                    self._ep_returns[i] = 0.0
        self._reward_history = self._reward_history[-100:]
        return {
            "episode_reward_mean": (float(np.mean(self._reward_history))
                                    if self._reward_history else 0.0),
            "num_env_steps_sampled": self._total_steps,
        }

    def get_weights(self):
        return {}

    def set_weights(self, weights) -> None:
        pass
