"""Contextual bandits: LinUCB and Linear Thompson Sampling.

Mirrors the reference's bandit algorithms (`rllib/algorithms/bandit/`):
per-arm linear models over context features with closed-form ridge
updates — no gradient descent, exact posterior. `training_step` pulls a
batch of arms from the env, observes rewards, and does the rank-1
Sherman-Morrison update per observation.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm


class LinearBanditEnv:
    """Contexts x ~ N(0,1)^d, reward = theta_a . x + noise. For tests."""

    def __init__(self, num_arms: int = 5, context_dim: int = 8,
                 noise: float = 0.1, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.theta = rng.standard_normal((num_arms, context_dim)) / np.sqrt(context_dim)
        self.num_arms = num_arms
        self.context_dim = context_dim
        self.noise = noise
        self._rng = np.random.default_rng(seed + 1)

    def observation(self) -> np.ndarray:
        return self._rng.standard_normal(self.context_dim).astype(np.float32)

    def reward(self, context: np.ndarray, arm: int) -> float:
        return float(self.theta[arm] @ context
                     + self._rng.normal(0, self.noise))

    def best_reward(self, context: np.ndarray) -> float:
        return float((self.theta @ context).max())


class _LinearBandit(Algorithm):
    """Shared ridge-regression state: per-arm A^-1 (precision) and b."""

    _explore: str = "ucb"

    def setup(self, config: Dict[str, Any]) -> None:
        self.env: LinearBanditEnv = config.get("env") or LinearBanditEnv()
        self.num_arms = self.env.num_arms
        self.d = self.env.context_dim
        self.alpha = float(config.get("alpha", 1.0))
        self.batch_size = int(config.get("batch_size", 32))
        self._rng = np.random.default_rng(int(config.get("seed", 0)))
        # A_inv starts at identity (ridge lambda=1), b at zero
        self.A_inv = np.stack([np.eye(self.d) for _ in range(self.num_arms)])
        self.b = np.zeros((self.num_arms, self.d))
        self._cumulative_regret = 0.0
        self._steps = 0

    def _select_arm(self, x: np.ndarray) -> int:
        theta_hat = np.einsum("adk,ak->ad", self.A_inv, self.b)
        if self._explore == "ucb":
            means = theta_hat @ x
            widths = np.sqrt(np.einsum("d,adk,k->a", x, self.A_inv, x))
            return int(np.argmax(means + self.alpha * widths))
        # Thompson: sample theta ~ N(theta_hat, alpha^2 A^-1)
        scores = np.empty(self.num_arms)
        for a in range(self.num_arms):
            sample = self._rng.multivariate_normal(
                theta_hat[a], self.alpha**2 * self.A_inv[a])
            scores[a] = sample @ x
        return int(np.argmax(scores))

    def _observe(self, x: np.ndarray, arm: int, reward: float) -> None:
        # Sherman-Morrison rank-1 update of A^-1
        Ainv = self.A_inv[arm]
        Ax = Ainv @ x
        self.A_inv[arm] = Ainv - np.outer(Ax, Ax) / (1.0 + x @ Ax)
        self.b[arm] += reward * x

    def training_step(self) -> Dict[str, Any]:
        rewards = []
        for _ in range(self.batch_size):
            x = self.env.observation()
            arm = self._select_arm(x)
            r = self.env.reward(x, arm)
            self._observe(x, arm, r)
            self._cumulative_regret += self.env.best_reward(x) - r
            self._steps += 1
            rewards.append(r)
        return {
            "episode_reward_mean": float(np.mean(rewards)),
            "cumulative_regret": float(self._cumulative_regret),
            "regret_per_step": float(self._cumulative_regret / self._steps),
            "num_env_steps_sampled": self._steps,
        }

    def compute_action(self, context: np.ndarray) -> int:
        return self._select_arm(np.asarray(context, np.float64))

    def get_weights(self):
        return {"A_inv": self.A_inv.copy(), "b": self.b.copy()}

    def set_weights(self, weights) -> None:
        self.A_inv = np.asarray(weights["A_inv"]).copy()
        self.b = np.asarray(weights["b"]).copy()


class BanditLinUCB(_LinearBandit):
    """UCB exploration: argmax mean + alpha * confidence width."""

    _explore = "ucb"


class BanditLinTS(_LinearBandit):
    """Posterior (Thompson) sampling over the per-arm linear model."""

    _explore = "ts"
