"""SAC: soft actor-critic for continuous control.

Mirrors the reference's SAC (`rllib/algorithms/sac/sac.py`): off-policy
replay, twin soft Q critics with target networks, a tanh-squashed Gaussian
policy, and automatic entropy-temperature tuning. Sampling runs on env
actors; the learner is one jitted JAX update (critic + actor + alpha in a
single step, polyak target sync).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import ContinuousVectorEnv, PendulumEnv
from ray_tpu.rllib.models import init_mlp, mlp_forward
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.replay_buffers import ReplayBuffer

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def init_sac_params(seed: int, obs_dim: int, action_dim: int,
                    hidden: Tuple[int, ...] = (256, 256)) -> Dict[str, Any]:
    rng = np.random.default_rng(seed)
    return {
        "actor": init_mlp(rng, (obs_dim, *hidden, 2 * action_dim),
                          final_scale=0.01),
        "q1": init_mlp(rng, (obs_dim + action_dim, *hidden, 1)),
        "q2": init_mlp(rng, (obs_dim + action_dim, *hidden, 1)),
    }


def actor_dist(actor_params, obs, action_dim: int):
    """Returns (mean, log_std) of the pre-squash Gaussian."""
    import jax.numpy as jnp

    out = mlp_forward(actor_params, obs, len(actor_params) // 2)
    mean, log_std = out[..., :action_dim], out[..., action_dim:]
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    return mean, log_std


def sample_action(actor_params, obs, key, action_dim: int, max_action: float):
    """Reparameterized tanh-Gaussian sample with log-prob correction."""
    import jax
    import jax.numpy as jnp

    mean, log_std = actor_dist(actor_params, obs, action_dim)
    std = jnp.exp(log_std)
    eps = jax.random.normal(key, mean.shape)
    pre = mean + std * eps
    a = jnp.tanh(pre)
    # log N(pre; mean, std) - sum log(1 - tanh^2) [change of variables]
    logp = (-0.5 * (eps**2 + 2 * log_std + jnp.log(2 * jnp.pi))).sum(-1)
    logp -= (2 * (jnp.log(2.0) - pre - jax.nn.softplus(-2 * pre))).sum(-1)
    return a * max_action, logp


def q_value(q_params, obs, action):
    import jax.numpy as jnp

    x = jnp.concatenate([obs, action], axis=-1)
    return mlp_forward(q_params, x, len(q_params) // 2)[..., 0]


class ContinuousWorkerBase:
    """Shared env-actor loop for continuous control: random warmup phase,
    transition collection, episode-return bookkeeping.

    Acting is MODULE + CONNECTORS (reference EnvRunner + connector
    pipelines): subclasses provide `_make_module` and `_make_module_to_env`
    — the exploration policy is a pipeline edit (SampleAction for SAC's
    stochastic actor; SampleAction+GaussianNoise for DDPG/TD3), and the
    warmup phase is the RandomActions connector, not worker code."""

    def __init__(self, env_maker, num_envs: int, seed: int,
                 obs_dim: int, action_dim: int, max_action: float):
        from ray_tpu.rllib.connectors import (CastObsFloat32,
                                              ConnectorPipeline,
                                              RandomActions)

        self.vec = ContinuousVectorEnv(env_maker, num_envs, seed)
        self.obs = self.vec.reset()
        self.rng = np.random.default_rng(seed)
        self.params = None
        self.action_dim = action_dim
        self.max_action = max_action
        self.module = self._make_module(obs_dim, action_dim, max_action)
        self.env_to_module = ConnectorPipeline([CastObsFloat32()])
        self.module_to_env = self._make_module_to_env()
        self.random_warmup = ConnectorPipeline(
            [RandomActions(action_dim, -max_action, max_action)])
        self._ep_returns = np.zeros(num_envs, np.float32)
        self._completed: List[float] = []

    def set_weights(self, actor) -> bool:
        self.params = {k: np.asarray(v) for k, v in actor.items()}
        return True

    def _make_module(self, obs_dim: int, action_dim: int, max_action: float):
        raise NotImplementedError

    def _make_module_to_env(self):
        raise NotImplementedError

    def _act(self, random_policy: bool) -> np.ndarray:
        data = {"obs": self.obs, "rng": self.rng, "module": self.module,
                "params": self.params}
        data = self.env_to_module(data)
        if random_policy or self.params is None:
            data = self.random_warmup(data)
        else:
            data["fwd_out"] = self.module.forward_inference(self.params,
                                                            data["obs"])
            data = self.module_to_env(data)
        return np.asarray(data["actions"], np.float32)

    def sample(self, num_steps: int, random_policy: bool = False):
        cols = {k: [] for k in ("obs", "actions", "rewards", "next_obs", "dones")}
        for _ in range(num_steps):
            actions = self._act(random_policy)
            prev = self.obs
            self.obs, rewards, dones, _ = self.vec.step(actions)
            cols["obs"].append(prev)
            cols["actions"].append(actions)
            cols["rewards"].append(rewards)
            cols["next_obs"].append(self.obs)
            cols["dones"].append(dones.astype(np.float32))
            self._ep_returns += rewards
            for i, d in enumerate(dones):
                if d:
                    self._completed.append(float(self._ep_returns[i]))
                    self._ep_returns[i] = 0.0
        out = {k: np.concatenate(v) if v[0].ndim > 1 else np.stack(v).reshape(-1)
               for k, v in cols.items()}
        ep, self._completed = self._completed, []
        out["episode_returns"] = np.array(ep, np.float32)
        return out


@ray_tpu.remote
class ContinuousSampleWorker(ContinuousWorkerBase):
    """Env actor for SAC: SquashedGaussianModule + SampleAction."""

    def _make_module(self, obs_dim, action_dim, max_action):
        from ray_tpu.rllib.rl_module import SquashedGaussianModule

        return SquashedGaussianModule(obs_dim, action_dim, max_action)

    def _make_module_to_env(self):
        from ray_tpu.rllib.connectors import ConnectorPipeline, SampleAction

        return ConnectorPipeline([SampleAction(record_logp=False)])


class SACLearner(Learner):
    """Twin-Q soft policy iteration with auto-alpha, as ONE combined loss
    on the Learner stack: per-term stop_gradients give each parameter
    group exactly its own gradients (critic <- TD, actor <- reparameterized
    Q through FROZEN critics, log_alpha <- entropy temperature), and the
    polyak target sync is the jitted post_update hook (reference SAC via
    core/learner + additional_update_for_module)."""

    def __init__(self, obs_dim: int, action_dim: int, max_action: float,
                 lr: float, gamma: float, tau: float,
                 target_entropy: float, seed: int = 0, mesh=None):
        self._obs_dim = obs_dim
        self._action_dim = action_dim
        self._max_action = max_action
        self._gamma = gamma
        self._tau = tau
        self._target_entropy = target_entropy
        super().__init__(lr=lr, mesh=mesh, seed=seed)

    def init_params(self, seed: int):
        import jax.numpy as jnp

        p = init_sac_params(seed, self._obs_dim, self._action_dim)
        p["log_alpha"] = jnp.zeros(())
        return p

    def make_extra(self):
        return {"q1": {k: np.asarray(v).copy()
                       for k, v in self.params["q1"].items()},
                "q2": {k: np.asarray(v).copy()
                       for k, v in self.params["q2"].items()}}

    def post_update(self, params, extra):
        import jax

        return jax.tree_util.tree_map(
            lambda t, p: (1 - self._tau) * t + self._tau * p,
            extra, {"q1": params["q1"], "q2": params["q2"]})

    def loss(self, params, batch, extra, rng):
        import jax
        import jax.numpy as jnp

        sg = jax.lax.stop_gradient
        k1, k2 = jax.random.split(rng)
        alpha = jnp.exp(params["log_alpha"])

        # critic: TD toward entropy-regularized target-Q backup
        next_a, next_logp = sample_action(
            params["actor"], batch["next_obs"], k1,
            self._action_dim, self._max_action)
        tq = jnp.minimum(q_value(extra["q1"], batch["next_obs"], next_a),
                         q_value(extra["q2"], batch["next_obs"], next_a))
        backup = sg(batch["rewards"] + self._gamma * (1 - batch["dones"])
                    * (tq - alpha * next_logp))
        q1 = q_value(params["q1"], batch["obs"], batch["actions"])
        q2 = q_value(params["q2"], batch["obs"], batch["actions"])
        c_loss = ((q1 - backup) ** 2).mean() + ((q2 - backup) ** 2).mean()

        # actor: reparameterized sample through FROZEN critics
        a, logp = sample_action(params["actor"], batch["obs"], k2,
                                self._action_dim, self._max_action)
        q_pi = jnp.minimum(q_value(sg(params["q1"]), batch["obs"], a),
                           q_value(sg(params["q2"]), batch["obs"], a))
        a_loss = (sg(alpha) * logp - q_pi).mean()

        # temperature toward the target entropy
        alpha_loss = (-jnp.exp(params["log_alpha"])
                      * sg(logp + self._target_entropy)).mean()

        total = c_loss + a_loss + alpha_loss
        return total, {"critic_loss": c_loss, "actor_loss": a_loss,
                       "alpha": sg(alpha), "entropy": -sg(logp).mean()}

    def update_batch(self, batch) -> Dict[str, float]:
        import jax

        aux = self.update(batch)
        return {k: float(v) for k, v in jax.device_get(aux).items()}

    def set_weights(self, weights):
        super().set_weights(weights)
        self.extra = self.make_extra()


class SACConfig:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: PendulumEnv(seed)
        self.obs_dim = PendulumEnv.observation_dim
        self.action_dim = PendulumEnv.action_dim
        self.max_action = PendulumEnv.max_action
        self.num_rollout_workers = 1
        self.num_envs_per_worker = 1
        self.rollout_fragment_length = 64
        self.lr = 3e-4
        self.gamma = 0.99
        self.tau = 0.005
        self.target_entropy = None   # default: -action_dim
        self.buffer_size = 100_000
        self.train_batch_size = 256
        self.num_updates_per_step = 8
        self.learning_starts = 256
        self.seed = 0

    def environment(self, env_maker=None, *, obs_dim=None, action_dim=None,
                    max_action=None):
        if env_maker is not None:
            self.env_maker = env_maker
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if action_dim is not None:
            self.action_dim = action_dim
        if max_action is not None:
            self.max_action = max_action
        return self

    def rollouts(self, *, num_rollout_workers=None, num_envs_per_worker=None,
                 rollout_fragment_length=None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown SAC option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "SAC":
        return SAC({"sac_config": self})


class SAC(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        cfg: SACConfig = config.get("sac_config") or SACConfig()
        self.cfg = cfg
        target_entropy = (cfg.target_entropy if cfg.target_entropy is not None
                          else -float(cfg.action_dim))
        self.learner = SACLearner(
            cfg.obs_dim, cfg.action_dim, cfg.max_action, cfg.lr, cfg.gamma,
            cfg.tau, target_entropy, cfg.seed)
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self.workers = [
            ContinuousSampleWorker.options(num_cpus=1).remote(
                cfg.env_maker, cfg.num_envs_per_worker,
                cfg.seed + 1000 * (i + 1), cfg.obs_dim, cfg.action_dim,
                cfg.max_action)
            for i in range(cfg.num_rollout_workers)]
        self._broadcast_weights()
        self._reward_history: List[float] = []
        self._total_steps = 0

    def _broadcast_weights(self) -> None:
        from ray_tpu.rllib.learner import broadcast_weights

        broadcast_weights(self.learner.get_weights()["actor"], self.workers)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        random_phase = self._total_steps < cfg.learning_starts
        samples = ray_tpu.get([
            w.sample.remote(cfg.rollout_fragment_length, random_phase)
            for w in self.workers])
        for batch in samples:
            self.buffer.add_batch({
                k: batch[k] for k in
                ("obs", "actions", "rewards", "next_obs", "dones")})
            self._total_steps += int(batch["actions"].shape[0])
            self._reward_history.extend(batch["episode_returns"].tolist())
        self._reward_history = self._reward_history[-100:]
        stats: Dict[str, float] = {}
        if len(self.buffer) >= cfg.train_batch_size:
            for _ in range(cfg.num_updates_per_step):
                mb = self.buffer.sample(cfg.train_batch_size)
                stats = self.learner.update_batch({
                    k: mb[k] for k in
                    ("obs", "actions", "rewards", "next_obs", "dones")})
            self._broadcast_weights()
        return {
            "episode_reward_mean": (float(np.mean(self._reward_history))
                                    if self._reward_history else 0.0),
            "num_env_steps_sampled": self._total_steps,
            **stats,
        }

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        self.learner.set_weights(weights)
        self._broadcast_weights()

    def stop(self) -> None:
        self._kill_workers(self.workers)
