"""A3C: asynchronous advantage actor-critic.

Mirrors the reference's A3C (`rllib/algorithms/a3c/a3c.py`:
`training_step` harvests `compute_gradients` futures from workers and
applies them centrally, sending fresh weights only to the worker whose
gradient was consumed): each worker SAMPLES AND DIFFERENTIATES locally
(module + connector acting, then the A2C loss on its own CPU), the driver
applies gradients hogwild-style as they arrive — no synchronous barrier,
stale gradients by design.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.env import CartPoleEnv
from ray_tpu.rllib.ppo import RolloutWorkerImpl, compute_gae


class A3CWorkerImpl(RolloutWorkerImpl):
    """Rollout worker that also computes the A2C gradient on its own batch
    (reference a3c.py:186 `sample_and_compute_grads`)."""

    def init_learner(self, lr: float, vf_coeff: float, entropy_coeff: float,
                     gamma: float, lambda_: float, seed: int) -> bool:
        from ray_tpu.rllib.a2c import A2CLearner

        self._learner = A2CLearner(self.obs_dim, self.num_actions, lr,
                                   vf_coeff, entropy_coeff, seed,
                                   module=self.module)
        self._gamma = gamma
        self._lambda = lambda_
        return True

    def sample_and_grads(self, num_steps: int):
        import jax

        batch = self.sample(num_steps)
        adv, ret = compute_gae(batch, self._gamma, self._lambda)
        T, N = batch["actions"].shape
        flat = {
            "obs": batch["obs"].reshape(T * N, -1),
            "actions": batch["actions"].reshape(-1),
            "advantages": adv.reshape(-1),
            "returns": ret.reshape(-1),
        }
        a = flat["advantages"]
        flat["advantages"] = (a - a.mean()) / (a.std() + 1e-8)
        self._learner.params = jax.tree_util.tree_map(
            np.asarray, self.params)
        grads, aux = self._learner.compute_gradients(flat)
        grads = jax.tree_util.tree_map(np.asarray, jax.device_get(grads))
        return {
            "grads": grads,
            "episode_returns": batch["episode_returns"],
            "num_steps": T * N,
            "loss": float(aux["total_loss"]),
        }


A3CWorker = ray_tpu.remote(A3CWorkerImpl)


class A3CConfig:
    def __init__(self):
        self.env_maker: Callable[[int], Any] = lambda seed: CartPoleEnv(seed)
        self.obs_dim = CartPoleEnv.observation_dim
        self.num_actions = CartPoleEnv.num_actions
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 4
        self.rollout_fragment_length = 32
        self.lr = 1e-3
        self.gamma = 0.99
        self.lambda_ = 1.0
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grads_per_step = 4        # gradients harvested per train()
        self.seed = 0

    def environment(self, env_maker=None, *, obs_dim=None, num_actions=None):
        if env_maker is not None:
            self.env_maker = env_maker
        if obs_dim is not None:
            self.obs_dim = obs_dim
        if num_actions is not None:
            self.num_actions = num_actions
        return self

    def rollouts(self, *, num_rollout_workers=None, num_envs_per_worker=None,
                 rollout_fragment_length=None):
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown A3C option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "A3C":
        return A3C({"a3c_config": self})


class A3C(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        from ray_tpu.rllib.a2c import A2CLearner

        cfg: A3CConfig = config.get("a3c_config") or A3CConfig()
        self.cfg = cfg
        # central copy: owns the canonical params + optimizer state; worker
        # gradients are applied as they land
        self.learner = A2CLearner(cfg.obs_dim, cfg.num_actions, cfg.lr,
                                  cfg.vf_coeff, cfg.entropy_coeff, cfg.seed)
        self.workers = [
            A3CWorker.options(num_cpus=1).remote(
                cfg.env_maker, cfg.num_envs_per_worker,
                cfg.seed + 1000 * (i + 1), cfg.obs_dim, cfg.num_actions)
            for i in range(cfg.num_rollout_workers)]
        ray_tpu.get([wk.init_learner.remote(
            cfg.lr, cfg.vf_coeff, cfg.entropy_coeff, cfg.gamma, cfg.lambda_,
            cfg.seed) for wk in self.workers])
        w = self.learner.get_weights()
        ray_tpu.get([wk.set_weights.remote(w) for wk in self.workers])
        self._inflight: Dict[Any, int] = {}
        for i, wk in enumerate(self.workers):
            self._inflight[wk.sample_and_grads.remote(
                cfg.rollout_fragment_length)] = i
        self._reward_history: List[float] = []
        self._total_steps = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        losses = []
        harvested = 0
        while harvested < cfg.grads_per_step:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=60)
            if not ready:
                break
            fut = ready[0]
            widx = self._inflight.pop(fut)
            wk = self.workers[widx]
            try:
                out = ray_tpu.get(fut)
            except Exception:
                # worker died mid-sample: reissue on the (restarted) actor
                self._inflight[wk.sample_and_grads.remote(
                    cfg.rollout_fragment_length)] = widx
                continue
            harvested += 1
            # hogwild apply: the gradient is stale by however many applies
            # happened since this worker last synced — A3C's defining trait
            self.learner.apply_gradients(out["grads"])
            losses.append(out["loss"])
            self._total_steps += out["num_steps"]
            self._reward_history.extend(out["episode_returns"].tolist())
            # refresh ONLY this worker, then put it back to work
            wk.set_weights.remote(self.learner.get_weights())
            self._inflight[wk.sample_and_grads.remote(
                cfg.rollout_fragment_length)] = widx
        self._reward_history = self._reward_history[-100:]
        return {
            "episode_reward_mean": (float(np.mean(self._reward_history))
                                    if self._reward_history else 0.0),
            "num_env_steps_sampled": self._total_steps,
            "num_grads_applied": harvested,
            "loss": float(np.mean(losses)) if losses else float("nan"),
        }

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights) -> None:
        self.learner.set_weights(weights)
        w = self.learner.get_weights()
        ray_tpu.get([wk.set_weights.remote(w) for wk in self.workers])

    def stop(self) -> None:
        self._kill_workers(self.workers)
