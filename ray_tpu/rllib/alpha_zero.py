"""AlphaZero: MCTS self-play + policy/value network (Silver et al. 2017).

Mirrors the reference's AlphaZero (`rllib/algorithms/alpha_zero/`): PUCT
tree search guided by a policy/value net, self-play games generating
(state, visit-distribution, outcome) triples, and a jitted supervised
update (policy cross-entropy + value MSE). The board game is pluggable via
the `GameEnv` contract; `TicTacToeEnv` is the in-tree example (the
reference ships open_spiel connectors instead — an external dep this build
avoids).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.models import init_mlp, mlp_hidden


class TicTacToeEnv:
    """Canonical-player board game: observations are always from the
    perspective of the player to move (+1 own, -1 opponent)."""

    num_actions = 9
    observation_dim = 9

    def __init__(self):
        self.reset()

    def reset(self) -> np.ndarray:
        self.board = np.zeros(9, np.int8)
        self.player = 1
        return self.observation()

    def observation(self) -> np.ndarray:
        return (self.board * self.player).astype(np.float32)

    def legal_actions(self) -> List[int]:
        return [i for i in range(9) if self.board[i] == 0]

    _LINES = [(0, 1, 2), (3, 4, 5), (6, 7, 8), (0, 3, 6), (1, 4, 7),
              (2, 5, 8), (0, 4, 8), (2, 4, 6)]

    def winner(self) -> Optional[int]:
        """+1/-1 winner, 0 draw, None ongoing."""
        for a, b, c in self._LINES:
            s = int(self.board[a]) + int(self.board[b]) + int(self.board[c])
            if s == 3:
                return 1
            if s == -3:
                return -1
        if not (self.board == 0).any():
            return 0
        return None

    def step(self, action: int) -> Tuple[np.ndarray, Optional[float], bool]:
        """Returns (obs for the NEXT player, outcome for the MOVER, done)."""
        assert self.board[action] == 0, "illegal move"
        self.board[action] = self.player
        w = self.winner()
        self.player = -self.player
        if w is None:
            return self.observation(), None, False
        # outcome from the mover's perspective
        mover = -self.player
        return self.observation(), float(w * mover), True

    def clone(self) -> "TicTacToeEnv":
        e = TicTacToeEnv()
        e.board = self.board.copy()
        e.player = self.player
        return e


class _Node:
    __slots__ = ("prior", "visits", "value_sum", "children")

    def __init__(self, prior: float):
        self.prior = prior
        self.visits = 0
        self.value_sum = 0.0
        self.children: Dict[int, "_Node"] = {}

    @property
    def q(self) -> float:
        return self.value_sum / self.visits if self.visits else 0.0


class MCTS:
    """PUCT search over canonical game states."""

    def __init__(self, predict: Callable, n_simulations: int = 50,
                 c_puct: float = 1.5, dirichlet_alpha: float = 0.6,
                 noise_frac: float = 0.25, rng: Optional[np.random.Generator] = None):
        self.predict = predict
        self.n_sim = n_simulations
        self.c = c_puct
        self.alpha = dirichlet_alpha
        self.noise_frac = noise_frac
        self.rng = rng or np.random.default_rng(0)

    def policy(self, env: TicTacToeEnv, *, add_noise: bool = True
               ) -> np.ndarray:
        root = _Node(0.0)
        self._expand(root, env, add_noise=add_noise)
        for _ in range(self.n_sim):
            self._simulate(root, env.clone())
        visits = np.zeros(env.num_actions, np.float32)
        for a, child in root.children.items():
            visits[a] = child.visits
        total = visits.sum()
        return visits / total if total else visits

    def _expand(self, node: _Node, env: TicTacToeEnv, *,
                add_noise: bool = False) -> float:
        priors, value = self.predict(env.observation())
        legal = env.legal_actions()
        mask = np.zeros(env.num_actions, bool)
        mask[legal] = True
        p = np.where(mask, priors, 0.0)
        p = p / p.sum() if p.sum() > 0 else mask / mask.sum()
        if add_noise and legal:
            noise = self.rng.dirichlet([self.alpha] * len(legal))
            for i, a in enumerate(legal):
                p[a] = (1 - self.noise_frac) * p[a] + self.noise_frac * noise[i]
        for a in legal:
            node.children[a] = _Node(float(p[a]))
        return float(value)

    def _simulate(self, node: _Node, env: TicTacToeEnv) -> float:
        """Returns the value from the perspective of the player to move at
        `node`. Children hold the NEXT player's nodes, so values negate."""
        if not node.children:  # terminal or unexpanded leaf
            w = env.winner()
            if w is not None:
                return float(w * env.player)
            return self._expand(node, env)
        # PUCT select
        sqrt_total = math.sqrt(max(1, node.visits))
        best, best_score = None, -1e18
        for a, child in node.children.items():
            u = self.c * child.prior * sqrt_total / (1 + child.visits)
            score = -child.q + u  # child.q is from the opponent's view
            if score > best_score:
                best, best_score = a, score
        child = node.children[best]
        _, outcome, done = env.step(best)
        if done:
            # outcome is from the MOVER's (this node's player's)
            # perspective; the child holds the opponent's view
            v_child = -float(outcome)
        else:
            v_child = self._simulate(child, env)
        child.visits += 1
        child.value_sum += v_child   # child stats are the child player's view
        node.visits += 1
        return -v_child              # flip back to this node's player


class AlphaZeroConfig:
    def __init__(self):
        self.env_maker: Callable[[], Any] = TicTacToeEnv
        self.obs_dim = TicTacToeEnv.observation_dim
        self.num_actions = TicTacToeEnv.num_actions
        self.hidden = 64
        self.lr = 5e-3
        self.n_simulations = 40
        self.c_puct = 1.5
        self.games_per_iter = 12
        self.train_batch_size = 64
        self.updates_per_iter = 8
        self.buffer_capacity = 4000
        self.temperature_moves = 4   # sample pi^1 early, argmax after
        self.value_loss_weight = 1.0
        self.seed = 0

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown AlphaZero option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "AlphaZero":
        return AlphaZero({"az_config": self})


class AlphaZero(Algorithm):
    def setup(self, config: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp
        import optax

        cfg: AlphaZeroConfig = config.get("az_config") or AlphaZeroConfig()
        self.cfg = cfg
        rng = np.random.default_rng(cfg.seed)
        self._np_rng = rng
        h = cfg.hidden
        self.params = jax.tree_util.tree_map(jnp.asarray, {
            "trunk": init_mlp(rng, (cfg.obs_dim, h, h)),
            "policy": init_mlp(rng, (h, cfg.num_actions)),
            "value": init_mlp(rng, (h, 1)),
        })
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._buffer: List[Tuple[np.ndarray, np.ndarray, float]] = []

        def net(p, obs):
            x = mlp_hidden(p["trunk"], obs, 2)
            logits = x @ p["policy"]["w0"] + p["policy"]["b0"]
            value = jnp.tanh((x @ p["value"]["w0"] + p["value"]["b0"])[..., 0])
            return logits, value

        self._net = jax.jit(net)

        def loss_fn(p, batch):
            logits, value = net(p, batch["obs"])
            logp = jax.nn.log_softmax(logits)
            policy_loss = -(batch["pi"] * logp).sum(-1).mean()
            value_loss = ((value - batch["z"]) ** 2).mean()
            return policy_loss + cfg.value_loss_weight * value_loss

        def update(p, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(p, batch)
            updates, opt_state = self.optimizer.update(grads, opt_state, p)
            return optax.apply_updates(p, updates), opt_state, loss

        self._update = jax.jit(update)
        self._jax = jax
        self._jnp = jnp

    # ------------------------------------------------------------- predict
    def _predict(self, obs: np.ndarray) -> Tuple[np.ndarray, float]:
        logits, value = self._net(self.params, self._jnp.asarray(obs[None]))
        p = np.asarray(self._jax.nn.softmax(logits[0]))
        return p, float(value[0])

    def _mcts(self, n_simulations: Optional[int] = None) -> MCTS:
        return MCTS(self._predict,
                    n_simulations=n_simulations or self.cfg.n_simulations,
                    c_puct=self.cfg.c_puct, rng=self._np_rng)

    # ------------------------------------------------------------ self-play
    def _self_play(self) -> Tuple[int, int]:
        cfg = self.cfg
        env = cfg.env_maker()
        env.reset()
        mcts = self._mcts()
        history: List[Tuple[np.ndarray, np.ndarray, int]] = []
        move = 0
        while True:
            pi = mcts.policy(env)
            history.append((env.observation().copy(), pi, env.player))
            if move < cfg.temperature_moves:
                # float64 renormalize: float32 rounding can trip numpy's
                # sum-to-1 check in choice()
                p = pi.astype(np.float64)
                action = int(self._np_rng.choice(len(p), p=p / p.sum()))
            else:
                action = int(pi.argmax())
            _, outcome, done = env.step(action)
            move += 1
            if done:
                w = env.winner()
                for obs, pi_t, player in history:
                    z = float(w * player) if w is not None else 0.0
                    self._buffer.append((obs, pi_t, z))
                if len(self._buffer) > cfg.buffer_capacity:
                    self._buffer = self._buffer[-cfg.buffer_capacity:]
                return move, int(w or 0)

    # --------------------------------------------------------------- train
    def training_step(self) -> Dict[str, Any]:
        cfg = self.cfg
        lengths, outcomes = [], []
        for _ in range(cfg.games_per_iter):
            length, w = self._self_play()
            lengths.append(length)
            outcomes.append(w)

        losses = []
        if len(self._buffer) >= cfg.train_batch_size:
            for _ in range(cfg.updates_per_iter):
                idx = self._np_rng.integers(0, len(self._buffer),
                                            cfg.train_batch_size)
                obs = np.stack([self._buffer[i][0] for i in idx])
                pi = np.stack([self._buffer[i][1] for i in idx])
                z = np.asarray([self._buffer[i][2] for i in idx], np.float32)
                batch = {k: self._jnp.asarray(v)
                         for k, v in (("obs", obs), ("pi", pi), ("z", z))}
                self.params, self.opt_state, loss = self._update(
                    self.params, self.opt_state, batch)
                losses.append(float(loss))
        return {
            "mean_game_length": float(np.mean(lengths)),
            "draw_rate": float(np.mean([o == 0 for o in outcomes])),
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "buffer": len(self._buffer),
        }

    # ------------------------------------------------------------ evaluate
    def play_vs_random(self, games: int = 20, seed: int = 123,
                       n_simulations: Optional[int] = None
                       ) -> Dict[str, float]:
        """Greedy MCTS (no noise) vs a uniform-random opponent; the agent
        alternates playing first/second. Evaluation searches deeper than
        self-play by default (self-play trades depth for game throughput)."""
        rng = np.random.default_rng(seed)
        sims = n_simulations or max(self.cfg.n_simulations, 120)
        results = {"win": 0, "draw": 0, "loss": 0}
        for g in range(games):
            env = self.cfg.env_maker()
            env.reset()
            agent_player = 1 if g % 2 == 0 else -1
            mcts = self._mcts(n_simulations=sims)
            while env.winner() is None:
                if env.player == agent_player:
                    pi = mcts.policy(env, add_noise=False)
                    action = int(pi.argmax())
                else:
                    action = int(rng.choice(env.legal_actions()))
                env.step(action)
            w = env.winner()
            if w == 0:
                results["draw"] += 1
            elif w == agent_player:
                results["win"] += 1
            else:
                results["loss"] += 1
        return {k: v / games for k, v in results.items()}

    def get_weights(self):
        return self._jax.device_get(self.params)

    def set_weights(self, weights) -> None:
        self.params = self._jax.tree_util.tree_map(self._jnp.asarray, weights)
