"""Replay buffers: uniform + prioritized (proportional).

Capability parity with the reference's `rllib/utils/replay_buffers/`
(`replay_buffer.py`, `prioritized_replay_buffer.py`). Storage is columnar
numpy ring buffers — samples leave as ready-to-device batches.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform-sampling ring buffer over transition columns."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._store: Optional[Dict[str, np.ndarray]] = None
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        if self._store is None:
            self._store = {
                k: np.zeros((self.capacity, *np.asarray(v).shape[1:]),
                            np.asarray(v).dtype)
                for k, v in batch.items()}
        if n >= self.capacity:  # keep only the newest `capacity` rows
            for k in self._store:
                self._store[k][:] = np.asarray(batch[k])[n - self.capacity:]
            self._idx, self._size = 0, self.capacity
            return
        head = min(n, self.capacity - self._idx)
        for k in self._store:
            v = np.asarray(batch[k])
            self._store[k][self._idx:self._idx + head] = v[:head]
            if head < n:  # wrapped tail
                self._store[k][:n - head] = v[head:]
        self._idx = (self._idx + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._store.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization (reference
    `prioritized_replay_buffer.py`): P(i) ∝ p_i^alpha, importance weights
    w_i = (N * P(i))^-beta normalized by max."""

    def __init__(self, capacity: int, alpha: float = 0.6, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self._priorities = np.zeros(capacity, np.float64)
        self._max_priority = 1.0

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        start = self._idx
        super().add_batch(batch)
        for j in range(n):
            self._priorities[(start + j) % self.capacity] = \
                self._max_priority ** self.alpha

    def sample(self, batch_size: int, beta: float = 0.4) -> Dict[str, np.ndarray]:
        p = self._priorities[:self._size]
        probs = p / p.sum()
        idx = self._rng.choice(self._size, size=batch_size, p=probs)
        weights = (self._size * probs[idx]) ** (-beta)
        weights = weights / weights.max()
        out = {k: v[idx] for k, v in self._store.items()}
        out["weights"] = weights.astype(np.float32)
        out["batch_indexes"] = idx.astype(np.int64)
        return out

    def update_priorities(self, idx: np.ndarray, td_errors: np.ndarray) -> None:
        p = (np.abs(td_errors) + 1e-6)
        self._priorities[idx] = p ** self.alpha
        self._max_priority = max(self._max_priority, float(p.max()))
