"""Trainers: distributed training orchestration over actor worker groups.

Mirrors the reference's Train anatomy (SURVEY §3.4): `BaseTrainer.fit`
(`python/ray/train/base_trainer.py:555`) -> BackendExecutor creates a
placement group (`_internal/backend_executor.py:154`) -> WorkerGroup of
actors, one per host, each running the user `train_loop_per_worker` with a
session that streams results back -> TrainingIterator collects them.

TPU-first differences:
  - the worker group reserves a *slice-shaped* placement group (STRICT_PACK
    over hosts with the same `tpu_slice` label) so the group's JAX mesh
    rides ICI;
  - no torch.distributed rendezvous: each worker initializes JAX for its
    hosts' chips (multi-host via jax.distributed coordinator whose address
    is rendezvoused through the control-plane KV, replacing
    `_setup_torch_process_group`, reference train/torch/config.py:69);
  - gradient traffic never touches the runtime — it is XLA collectives
    inside the jitted step (same property as the reference, where NCCL
    bypasses Ray).
"""

from __future__ import annotations

import logging
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air import (
    Checkpoint,
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.air import session as air_session
from ray_tpu.core.exceptions import PlacementInfeasibleError
from ray_tpu.core.placement_group import placement_group, remove_placement_group
from ray_tpu.util.queue import Queue

logger = logging.getLogger(__name__)


@ray_tpu.remote
class TrainWorker:
    """One member of the worker group (reference: `_TrainSession`,
    train/_internal/session.py:63)."""

    def __init__(self, rank: int, world_size: int, result_queue: Queue,
                 coordinator: Optional[str] = None):
        self.rank = rank
        self.world_size = world_size
        self.queue = result_queue
        self.coordinator = coordinator

    def run(self, train_loop: Callable, config: Dict[str, Any],
            checkpoint: Optional[Checkpoint], dataset_shards: Optional[dict]) -> dict:
        def report_fn(metrics, ckpt):
            entry = {"rank": self.rank, "metrics": dict(metrics)}
            if ckpt is not None and self.rank == 0:
                entry["checkpoint"] = ckpt
            self.queue.put(entry)

        air_session._set_session(air_session._Session(
            self.rank, self.world_size, report_fn, checkpoint, dataset_shards))
        try:
            train_loop(config) if _takes_arg(train_loop) else train_loop()
            return {"rank": self.rank, "status": "done"}
        except Exception as e:
            return {"rank": self.rank, "status": "error",
                    "error": f"{e}\n{traceback.format_exc()}"}
        finally:
            air_session._set_session(None)


# reported-metric key -> exported Prometheus series (ray_tpu/grafana.py
# train dashboard panels)
_TRAIN_GAUGE_KEYS = {
    "loss": "ray_tpu_train_loss",
    "tokens_per_sec": "ray_tpu_train_tokens_per_sec",
    "step_time_s": "ray_tpu_train_step_seconds",
    "mfu": "ray_tpu_train_mfu",
    "checkpoint_save_seconds": "ray_tpu_checkpoint_save_seconds",
}


def _update_train_gauges(metrics: Dict[str, Any]) -> None:
    from ray_tpu.util.metrics import get_or_create

    for key, series in _TRAIN_GAUGE_KEYS.items():
        v = metrics.get(key)
        if isinstance(v, (int, float)):
            get_or_create("gauge", series, f"train {key}").set(float(v))


def _takes_arg(fn: Callable) -> bool:
    import inspect

    try:
        return len(inspect.signature(fn).parameters) > 0
    except (TypeError, ValueError):
        return False


class DataParallelTrainer:
    """Runs `train_loop_per_worker` on `scaling_config.num_workers` actors.

    (reference: `python/ray/train/data_parallel_trainer.py:56`)
    """

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self._train_loop = train_loop_per_worker
        self._config = dict(train_loop_config or {})
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self._datasets = dict(datasets or {})
        self._resume_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        failures_left = self.run_config.failure_config.max_failures
        checkpoint = self._resume_checkpoint
        history: List[Dict[str, Any]] = []
        # elastic _shrink mutates the scaling config; work on a per-fit
        # copy so the caller's object (and the next fit) keep the original
        import dataclasses as _dc

        original_sc = self.scaling_config
        self.scaling_config = _dc.replace(original_sc)
        try:
            return self._fit_loop(failures_left, checkpoint, history)
        finally:
            self.scaling_config = original_sc

    def _fit_loop(self, failures_left, checkpoint, history) -> Result:
        while True:
            result = self._fit_once(checkpoint)
            # the returned Result spans ALL attempts: a recovered run's
            # pre-failure iterations are part of its history
            history.extend(result.metrics_history)
            if result.error is None or failures_left == 0:
                result.metrics_history = history
                return result
            failures_left -= 1
            checkpoint = result.checkpoint or checkpoint
            if (self.scaling_config.elastic
                    and isinstance(result.error, PlacementInfeasibleError)
                    and not self._shrink()):
                result.metrics_history = history
                return result  # nothing left to shrink to
            logger.warning("training attempt failed (%s); restarting "
                           "(%d retries left)", result.error, failures_left)

    def _shrink(self) -> bool:
        """Elastic topology shrink after a node/slice loss: halve the worker
        count first (fewest moving parts), then the per-worker chip grant.
        Returns False when already at 1 worker x 1 chip."""
        sc = self.scaling_config
        if sc.num_workers > 1:
            sc.num_workers = max(1, sc.num_workers // 2)
        elif sc.resources_per_worker and sc.resources_per_worker.get("TPU", 0) > 1:
            sc.resources_per_worker = dict(sc.resources_per_worker)
            sc.resources_per_worker["TPU"] = max(
                1.0, sc.resources_per_worker["TPU"] // 2)
        elif (sc.resources_per_worker is None and sc.use_tpu
              and sc.chips_per_worker > 1):
            # (chips_per_worker only reaches worker_resources() when
            # resources_per_worker is unset)
            sc.chips_per_worker = max(1, sc.chips_per_worker // 2)
        else:
            return False
        logger.warning("elastic shrink: retrying with num_workers=%d, "
                       "resources=%s", sc.num_workers, sc.worker_resources())
        return True

    def _fit_once(self, checkpoint: Optional[Checkpoint]) -> Result:
        sc = self.scaling_config
        n = sc.num_workers
        bundle = sc.worker_resources()
        pg = placement_group([dict(bundle) for _ in range(n)], strategy=sc.strategy())
        if not pg.ready(timeout=60):
            remove_placement_group(pg)
            return Result(metrics={}, error=PlacementInfeasibleError(
                f"placement group infeasible: {n} x {bundle}"))
        queue = Queue()
        shards = self._make_dataset_shards(n)
        workers: List[Any] = []
        try:
            workers = [
                TrainWorker.options(
                    placement_group=pg, placement_group_bundle_index=i,
                    resources=dict(bundle),
                ).remote(i, n, queue)
                for i in range(n)
            ]
            run_refs = [
                w.run.remote(self._train_loop, self._config, checkpoint,
                             shards[i] if shards else None)
                for i, w in enumerate(workers)
            ]
            return self._collect(queue, run_refs)
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            remove_placement_group(pg)

    def _make_dataset_shards(self, n: int) -> Optional[List[dict]]:
        if not self._datasets:
            return None
        shards: List[dict] = [dict() for _ in range(n)]
        for name, ds in self._datasets.items():
            if hasattr(ds, "streaming_split"):
                for i, it in enumerate(ds.streaming_split(n)):
                    shards[i][name] = it
            else:
                for i in range(n):
                    shards[i][name] = ds
        return shards

    def _collect(self, queue: Queue, run_refs) -> Result:
        ckpt_cfg = self.run_config.checkpoint_config
        history: List[Dict[str, Any]] = []
        checkpoints: List[tuple] = []  # (score, Checkpoint)
        latest_ckpt: Optional[Checkpoint] = None
        pending = list(run_refs)
        error: Optional[Exception] = None
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1, timeout=0.2)
            for entry in queue.get_batch(1000):
                if "metrics" in entry and entry["rank"] == 0:
                    history.append(entry["metrics"])
                    _update_train_gauges(entry["metrics"])
                if "checkpoint" in entry:
                    latest_ckpt = entry["checkpoint"]
                    score = None
                    if ckpt_cfg.checkpoint_score_attribute:
                        score = entry.get("metrics", {}).get(
                            ckpt_cfg.checkpoint_score_attribute)
                    checkpoints.append((score, latest_ckpt))
                    if ckpt_cfg.num_to_keep:
                        checkpoints = self._prune(checkpoints, ckpt_cfg)
            for ref in done:
                try:
                    status = ray_tpu.get(ref)
                    if status.get("status") == "error":
                        error = RuntimeError(status["error"])
                except Exception as e:
                    error = e
        # drain any remaining reports
        for entry in queue.get_batch(10000):
            if "metrics" in entry and entry["rank"] == 0:
                history.append(entry["metrics"])
            if "checkpoint" in entry:
                latest_ckpt = entry["checkpoint"]
        best = self._best_checkpoint(checkpoints, ckpt_cfg) or latest_ckpt
        return Result(
            metrics=history[-1] if history else {},
            checkpoint=best,
            error=error,
            metrics_history=history,
        )

    @staticmethod
    def _prune(checkpoints: List[tuple], cfg: CheckpointConfig) -> List[tuple]:
        if cfg.checkpoint_score_attribute is None:
            return checkpoints[-cfg.num_to_keep:]
        reverse = cfg.checkpoint_score_order == "max"
        ranked = sorted([c for c in checkpoints if c[0] is not None],
                        key=lambda t: t[0], reverse=reverse)
        unscored = [c for c in checkpoints if c[0] is None]
        return (ranked + unscored)[:cfg.num_to_keep]

    def _best_checkpoint(self, checkpoints, cfg) -> Optional[Checkpoint]:
        if not checkpoints:
            return None
        scored = [c for c in checkpoints if c[0] is not None]
        if cfg.checkpoint_score_attribute and scored:
            reverse = cfg.checkpoint_score_order == "max"
            return sorted(scored, key=lambda t: t[0], reverse=reverse)[0][1]
        return checkpoints[-1][1]


class JaxTrainer(DataParallelTrainer):
    """DataParallelTrainer whose workers drive TPU chips through JAX.

    The torch-era `TorchTrainer` equivalent (reference
    `python/ray/train/torch/torch_trainer.py`): instead of wrapping models
    in DDP, the train loop builds a `Mesh` over the worker's chips via
    `ray_tpu.parallel` and runs a pjit'd step; `prepare_mesh()` below is the
    analog of `prepare_model` — it resolves the worker's mesh from the
    scaling config.
    """

    @staticmethod
    def prepare_mesh(mesh_config=None):
        import jax

        from ray_tpu.parallel import MeshConfig, make_mesh

        cfg = mesh_config or MeshConfig()
        return make_mesh(cfg, jax.devices())
