"""SklearnTrainer + SklearnPredictor: CPU estimator training as a trial.

Reference parity: python/ray/train/sklearn/sklearn_trainer.py (fit an
estimator on AIR datasets in a remote task, optionally cross-validate,
checkpoint the fitted model) and sklearn_predictor.py. Training runs as a
single remote CPU task — there is nothing to shard onto chips, so unlike
DataParallelTrainer no worker group or mesh is involved.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.air import Checkpoint, Result, RunConfig
from ray_tpu.train.predictor import Predictor

MODEL_KEY = "estimator"


def _dataset_to_xy(ds, label_column: str,
                   feature_cols: Optional[List[str]] = None):
    rows = ds.take_all()
    if not rows:
        raise ValueError("empty dataset")
    if feature_cols is None:
        feature_cols = [c for c in rows[0] if c != label_column]
    X = np.asarray([[row[c] for c in feature_cols] for row in rows])
    y = np.asarray([row[label_column] for row in rows])
    return X, y, feature_cols


@ray_tpu.remote
def _fit_task(estimator, label_column: str, datasets: Dict[str, Any],
              cv: Optional[int], scoring: Optional[str],
              fit_params: Dict[str, Any]) -> dict:
    X, y, feature_cols = _dataset_to_xy(datasets["train"], label_column)
    metrics: Dict[str, Any] = {}
    if cv:
        from sklearn.model_selection import cross_val_score

        scores = cross_val_score(estimator, X, y, cv=cv, scoring=scoring)
        metrics["cv/mean_score"] = float(scores.mean())
        metrics["cv/std_score"] = float(scores.std())
    estimator.fit(X, y, **fit_params)
    metrics["train/score"] = float(estimator.score(X, y))
    for name, ds in datasets.items():
        if name == "train":
            continue
        Xv, yv, _ = _dataset_to_xy(ds, label_column, feature_cols)
        metrics[f"{name}/score"] = float(estimator.score(Xv, yv))
    return {"metrics": metrics, "estimator": estimator,
            "feature_cols": feature_cols}


class SklearnTrainer:
    """Fits a scikit-learn estimator on the "train" dataset in a remote CPU
    task; extra datasets are scored as validation sets."""

    def __init__(self, *, estimator, label_column: str,
                 datasets: Dict[str, Any],
                 cv: Optional[int] = None,
                 scoring: Optional[str] = None,
                 fit_params: Optional[Dict[str, Any]] = None,
                 run_config: Optional[RunConfig] = None):
        if "train" not in datasets:
            raise ValueError("datasets must contain a 'train' key")
        self._estimator = estimator
        self._label = label_column
        self._datasets = datasets
        self._cv = cv
        self._scoring = scoring
        self._fit_params = dict(fit_params or {})
        self.run_config = run_config or RunConfig()

    def fit(self) -> Result:
        try:
            out = ray_tpu.get(_fit_task.remote(
                self._estimator, self._label, self._datasets,
                self._cv, self._scoring, self._fit_params))
        except Exception as e:  # surface as Result like other trainers
            return Result(metrics={}, error=e)
        checkpoint = Checkpoint.from_dict({
            MODEL_KEY: out["estimator"],
            "feature_cols": out["feature_cols"]})
        return Result(metrics=out["metrics"], checkpoint=checkpoint)


class SklearnPredictor(Predictor):
    """Predicts with a fitted estimator restored from a checkpoint."""

    def __init__(self, estimator,
                 feature_cols: Optional[List[str]] = None):
        super().__init__()
        self._estimator = estimator
        self._feature_cols = feature_cols

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint) -> "SklearnPredictor":
        data = checkpoint.to_dict()
        return cls(data[MODEL_KEY], data.get("feature_cols"))

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        # reorder by the training-time feature columns — dict insertion
        # order of the caller's batch must not matter
        if self._feature_cols is not None and all(
                c in batch for c in self._feature_cols):
            cols = [np.asarray(batch[c]) for c in self._feature_cols]
        else:
            cols = [np.asarray(v) for v in batch.values()]
        X = np.stack(cols, axis=1) if cols[0].ndim == 1 else cols[0]
        return {"predictions": np.asarray(self._estimator.predict(X))}
