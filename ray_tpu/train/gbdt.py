"""GBDT trainers: distributed XGBoost / LightGBM on the worker group.

Reference parity: `python/ray/train/xgboost/xgboost_trainer.py:17` and
`python/ray/train/lightgbm/lightgbm_trainer.py` (both built on xgboost-ray
/ lightgbm-ray).

Distribution choice (and why): each library's OWN collective protocol over
this framework's worker task group — xgboost's RabitTracker + allreduce'd
histograms, LightGBM's socket machines-list — exactly the reference's
xgboost-ray architecture. The alternative (single-node-per-trial, scaled
via Tune) wastes the libraries' built-in data parallelism and caps dataset
size at one host's memory; with tracker-based training the framework only
has to shard rows and hand out rendezvous env vars, which the existing
task/actor machinery already does. With ONE worker no tracker is started
and training is the library's plain `train()`.

The scaffolding (row sharding, rendezvous, result/checkpoint plumbing) is
library-agnostic and test-covered via an in-repo "mock" backend; the
xgboost/lightgbm backends import their library lazily IN the worker, so
this module imports (and the trainers raise a clear error) on images
without them.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.air import Checkpoint, Result, RunConfig
from ray_tpu.train.sklearn import _dataset_to_xy

MODEL_KEY = "gbdt_model"
BACKEND_KEY = "gbdt_backend"


# ---------------------------------------------------------------- backends


class _XGBoostBackend:
    """xgboost.collective (rabit) training; histogram allreduce across the
    worker group."""

    name = "xgboost"

    @staticmethod
    def check_available() -> None:
        try:
            import xgboost  # noqa: F401
        except ImportError:
            raise ImportError(
                "XGBoostTrainer requires the xgboost package; it is not "
                "installed in this environment") from None

    @staticmethod
    def start_tracker(world: int) -> Tuple[Any, Dict[str, Any]]:
        """RabitTracker rendezvous (driver-side); returns (tracker,
        per-worker env). API differs across xgboost versions — handled by
        feature probes."""
        from xgboost.tracker import RabitTracker

        tracker = RabitTracker(host_ip="127.0.0.1", n_workers=world)
        tracker.start(world) if _wants_arg(tracker.start) else tracker.start()
        if hasattr(tracker, "worker_args"):
            env = dict(tracker.worker_args())
        else:
            env = dict(tracker.worker_envs())
        return tracker, env

    @staticmethod
    def finish_tracker(tracker) -> None:
        for meth in ("wait_for", "join"):
            fn = getattr(tracker, meth, None)
            if fn is not None:
                try:
                    fn()
                except Exception:  # third-party tracker teardown: best-effort
                    pass
                return

    @staticmethod
    def train_shard(rank: int, world: int, tracker_env: Dict[str, Any],
                    X, y, Xv, yv, params: dict, num_rounds: int):
        import xgboost as xgb

        def _run():
            evals_result: Dict[str, Any] = {}
            dtrain = xgb.DMatrix(X, label=y)
            evals = [(dtrain, "train")]
            if Xv is not None:
                evals.append((xgb.DMatrix(Xv, label=yv), "valid"))
            bst = xgb.train(params, dtrain, num_boost_round=num_rounds,
                            evals=evals, evals_result=evals_result,
                            verbose_eval=False)
            return bytes(bst.save_raw()), evals_result

        if world == 1:
            return _run()
        from xgboost import collective

        env = dict(tracker_env)
        env.setdefault("DMLC_TASK_ID", str(rank))
        with collective.CommunicatorContext(**env):
            model, evals_result = _run()
            return (model, evals_result) if collective.get_rank() == 0 \
                else (None, evals_result)

    @staticmethod
    def predict(model_bytes: bytes, X) -> np.ndarray:
        import xgboost as xgb

        bst = xgb.Booster()
        bst.load_model(bytearray(model_bytes))
        return np.asarray(bst.predict(xgb.DMatrix(X)))


class _LightGBMBackend:
    """LightGBM socket machines-list training."""

    name = "lightgbm"

    @staticmethod
    def check_available() -> None:
        try:
            import lightgbm  # noqa: F401
        except ImportError:
            raise ImportError(
                "LightGBMTrainer requires the lightgbm package; it is not "
                "installed in this environment") from None

    @staticmethod
    def start_tracker(world: int) -> Tuple[Any, Dict[str, Any]]:
        import socket

        ports = []
        socks = []
        for _ in range(world):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            socks.append(s)
        for s in socks:  # free them for lightgbm to rebind
            s.close()
        machines = ",".join(f"127.0.0.1:{p}" for p in ports)
        return None, {"machines": machines, "ports": ports}

    @staticmethod
    def finish_tracker(tracker) -> None:
        pass

    @staticmethod
    def train_shard(rank: int, world: int, tracker_env: Dict[str, Any],
                    X, y, Xv, yv, params: dict, num_rounds: int):
        import lightgbm as lgb

        params = dict(params)
        evals_result: Dict[str, Any] = {}
        if world > 1:
            params.update({
                "num_machines": world,
                "machines": tracker_env["machines"],
                "local_listen_port": tracker_env["ports"][rank],
                "tree_learner": params.get("tree_learner", "data"),
            })
        dtrain = lgb.Dataset(X, label=y)
        valid_sets = [dtrain]
        valid_names = ["train"]
        if Xv is not None:
            valid_sets.append(lgb.Dataset(Xv, label=yv, reference=dtrain))
            valid_names.append("valid")
        bst = lgb.train(params, dtrain, num_boost_round=num_rounds,
                        valid_sets=valid_sets, valid_names=valid_names,
                        callbacks=[lgb.record_evaluation(evals_result)])
        model = bst.model_to_string().encode() if rank == 0 else None
        return model, evals_result

    @staticmethod
    def predict(model_bytes: bytes, X) -> np.ndarray:
        import lightgbm as lgb

        bst = lgb.Booster(model_str=model_bytes.decode())
        return np.asarray(bst.predict(X))


class _MockBackend:
    """In-repo scaffolding backend: a constant-mean 'model' whose training
    exercises the exact shard/rendezvous/aggregate path, so the trainer
    machinery stays test-covered on images without xgboost/lightgbm."""

    name = "mock"

    @staticmethod
    def check_available() -> None:
        pass

    @staticmethod
    def start_tracker(world: int):
        return None, {"world": world}

    @staticmethod
    def finish_tracker(tracker) -> None:
        pass

    @staticmethod
    def train_shard(rank, world, tracker_env, X, y, Xv, yv, params,
                    num_rounds):
        import pickle

        if world > 1:  # rendezvous env only exists with a tracker
            assert tracker_env.get("world") == world
        model = pickle.dumps({"mean": float(np.mean(y)),
                              "n": len(y), "rank": rank}) \
            if rank == 0 else None
        metrics = {"train": {"rmse": [float(np.std(y))] * num_rounds}}
        return model, metrics

    @staticmethod
    def predict(model_bytes: bytes, X) -> np.ndarray:
        import pickle

        return np.full(len(X), pickle.loads(model_bytes)["mean"])


_BACKENDS = {b.name: b for b in
             (_XGBoostBackend, _LightGBMBackend, _MockBackend)}


def _wants_arg(fn) -> bool:
    import inspect

    try:
        return len(inspect.signature(fn).parameters) >= 1
    except (TypeError, ValueError):
        return False


# ----------------------------------------------------------------- trainer


@ray_tpu.remote
def _gbdt_train_task(backend_name: str, rank: int, world: int,
                     tracker_env: Dict[str, Any], X, y, Xv, yv,
                     params: dict, num_rounds: int):
    return _BACKENDS[backend_name].train_shard(
        rank, world, tracker_env, X, y, Xv, yv, params, num_rounds)


class GBDTTrainer:
    """Distributed gradient-boosted-tree training over the task group:
    rows shard across `num_workers`, the library's own collective syncs
    tree construction, rank 0's serialized model becomes the Checkpoint."""

    _backend_name = "mock"

    def __init__(self, *, label_column: str, params: Optional[dict] = None,
                 datasets: Dict[str, Any], num_workers: int = 2,
                 num_boost_round: int = 10,
                 run_config: Optional[RunConfig] = None):
        if "train" not in datasets:
            raise ValueError("datasets must contain a 'train' key")
        _BACKENDS[self._backend_name].check_available()
        self._label = label_column
        self._params = dict(params or {})
        self._datasets = datasets
        self._num_workers = max(1, num_workers)
        self._num_rounds = num_boost_round
        self.run_config = run_config or RunConfig()

    def fit(self) -> Result:
        backend = _BACKENDS[self._backend_name]
        try:
            X, y, feature_cols = _dataset_to_xy(
                self._datasets["train"], self._label)
            Xv = yv = None
            if "valid" in self._datasets:
                Xv, yv, _ = _dataset_to_xy(self._datasets["valid"],
                                           self._label, feature_cols)
            world = min(self._num_workers, len(y))
            tracker = None
            tracker_env: Dict[str, Any] = {}
            if world > 1:
                tracker, tracker_env = backend.start_tracker(world)
            shards = [(X[i::world], y[i::world]) for i in range(world)]
            futs = [_gbdt_train_task.options(num_cpus=1).remote(
                self._backend_name, rank, world, tracker_env,
                Xs, ys, Xv, yv, self._params, self._num_rounds)
                for rank, (Xs, ys) in enumerate(shards)]
            results = ray_tpu.get(futs, timeout=600)
            backend.finish_tracker(tracker)
        except Exception as e:
            return Result(metrics={}, error=e)
        model = next((m for m, _ in results if m is not None), None)
        if model is None:
            return Result(metrics={}, error=RuntimeError(
                "no worker produced a model"))
        evals = results[0][1]
        metrics = {f"{ds}/{k}": v[-1] for ds, series in evals.items()
                   for k, v in series.items() if v}
        checkpoint = Checkpoint.from_dict({
            MODEL_KEY: model, BACKEND_KEY: self._backend_name,
            "feature_cols": feature_cols})
        return Result(metrics=metrics, checkpoint=checkpoint)


class XGBoostTrainer(GBDTTrainer):
    """Reference `python/ray/train/xgboost/xgboost_trainer.py:17`."""

    _backend_name = "xgboost"


class LightGBMTrainer(GBDTTrainer):
    """Reference `python/ray/train/lightgbm/lightgbm_trainer.py`."""

    _backend_name = "lightgbm"


# --------------------------------------------------------------- predictor


class GBDTPredictor:
    """Predicts with a serialized booster from a GBDT checkpoint
    (reference xgboost_predictor.py / lightgbm_predictor.py)."""

    def __init__(self, model_bytes: bytes, backend_name: str,
                 feature_cols: Optional[List[str]] = None):
        self._model = model_bytes
        self._backend = _BACKENDS[backend_name]
        self._feature_cols = feature_cols

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint) -> "GBDTPredictor":
        data = checkpoint.to_dict()
        return cls(data[MODEL_KEY], data[BACKEND_KEY],
                   data.get("feature_cols"))

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        if self._feature_cols is not None and all(
                c in batch for c in self._feature_cols):
            cols = [np.asarray(batch[c]) for c in self._feature_cols]
        else:
            cols = [np.asarray(v) for v in batch.values()]
        X = np.stack(cols, axis=1) if cols[0].ndim == 1 else cols[0]
        return {"predictions": self._backend.predict(self._model, X)}


XGBoostPredictor = GBDTPredictor
LightGBMPredictor = GBDTPredictor
