"""Sharded checkpointing for TPU-scale training state (orbax-backed).

The AIR `Checkpoint` (air/checkpoint.py) is the small-payload control-plane
object the reference has; this module is the TPU-era data plane for model
state: orbax writes each shard from the device that owns it (no host
gather), and restore maps shards onto the *target* mesh's shardings — which
may differ from the save-time mesh. That mesh-reshape restore is the core
of elastic recovery (SURVEY hard-part #7: slice loss -> rebuild a smaller
mesh -> restore -> continue).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

import jax

logger = logging.getLogger(__name__)


_last_save_seconds: list = []


def pop_last_save_seconds() -> Any:
    """Most recent save_sharded duration, consumed by the next
    session.report so the driver can export it (save runs in worker
    processes whose metric registries the dashboard never scrapes)."""
    return _last_save_seconds.pop() if _last_save_seconds else None


def save_sharded(state: Any, path: str) -> str:
    """Write a (possibly sharded) pytree checkpoint; returns the path."""
    import time

    import orbax.checkpoint as ocp

    t0 = time.monotonic()
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)
    _last_save_seconds[:] = [time.monotonic() - t0]
    from ray_tpu.util.metrics import get_or_create

    get_or_create("gauge", "ray_tpu_checkpoint_save_seconds",
                  "last checkpoint save time").set(_last_save_seconds[0])
    return path


def restore_sharded(path: str, target: Any) -> Any:
    """Restore into `target`'s structure/shardings.

    `target` is a pytree of arrays OR jax.ShapeDtypeStruct leaves carrying
    `sharding` — typically built with `abstract_like(state, shardings)` for
    a mesh that need not match the one the checkpoint was saved from
    (shards are re-laid-out on read).
    """
    import orbax.checkpoint as ocp

    # abstract_like passes ShapeDtypeStruct leaves through unchanged (they
    # carry .shape/.dtype/.sharding), so mixed/concrete targets all work
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(os.path.abspath(path), abstract_like(target))


def abstract_like(state: Any, shardings: Optional[Any] = None) -> Any:
    """ShapeDtypeStruct skeleton of `state`, with per-leaf shardings (from
    the matching pytree, or each leaf's current sharding when None)."""
    if shardings is None:
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None)),
            state)
    return jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        state, shardings)
