"""Sharded checkpointing for TPU-scale training state (orbax-backed).

The AIR `Checkpoint` (air/checkpoint.py) is the small-payload control-plane
object the reference has; this module is the TPU-era data plane for model
state: orbax writes each shard from the device that owns it (no host
gather), and restore maps shards onto the *target* mesh's shardings — which
may differ from the save-time mesh. That mesh-reshape restore is the core
of elastic recovery (SURVEY hard-part #7: slice loss -> rebuild a smaller
mesh -> restore -> continue).
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
from typing import Any, Optional, Tuple

import jax

logger = logging.getLogger(__name__)


_last_save_seconds: list = []


def pop_last_save_seconds() -> Any:
    """Most recent save_sharded duration, consumed by the next
    session.report so the driver can export it (save runs in worker
    processes whose metric registries the dashboard never scrapes)."""
    return _last_save_seconds.pop() if _last_save_seconds else None


def save_sharded(state: Any, path: str) -> str:
    """Write a (possibly sharded) pytree checkpoint; returns the path."""
    import time

    import orbax.checkpoint as ocp

    t0 = time.monotonic()
    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)
    _last_save_seconds[:] = [time.monotonic() - t0]
    from ray_tpu.util.metrics import get_or_create

    get_or_create("gauge", "ray_tpu_checkpoint_save_seconds",
                  "last checkpoint save time").set(_last_save_seconds[0])
    return path


def restore_sharded(path: str, target: Any) -> Any:
    """Restore into `target`'s structure/shardings.

    `target` is a pytree of arrays OR jax.ShapeDtypeStruct leaves carrying
    `sharding` — typically built with `abstract_like(state, shardings)` for
    a mesh that need not match the one the checkpoint was saved from
    (shards are re-laid-out on read).
    """
    import orbax.checkpoint as ocp

    # abstract_like passes ShapeDtypeStruct leaves through unchanged (they
    # carry .shape/.dtype/.sharding), so mixed/concrete targets all work
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(os.path.abspath(path), abstract_like(target))


def abstract_like(state: Any, shardings: Optional[Any] = None) -> Any:
    """ShapeDtypeStruct skeleton of `state`, with per-leaf shardings (from
    the matching pytree, or each leaf's current sharding when None)."""
    if shardings is None:
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None)),
            state)
    return jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        state, shardings)


# ---------------------------------------------------------------------------
# Step-numbered checkpoint directories with atomic completion, a
# latest-complete pointer, and keep-last-K retention.  This is the restart
# contract the RL fleet learner (rllib/fleet.py) builds on: a crash between
# "orbax finished writing" and "rename landed" leaves only a torn .tmp-*
# directory that latest_checkpoint() never resolves, so restart always
# resumes from a checkpoint whose state AND meta are both fully on disk.
# ---------------------------------------------------------------------------

_STEP_DIR_RE = re.compile(r"^step_(\d+)$")
_TMP_PREFIX = ".tmp-"
_META_NAME = "meta.json"
_STATE_NAME = "state"


def checkpoint_path(root: str, step: int) -> str:
    return os.path.join(os.path.abspath(root), f"step_{step}")


def save_checkpoint(state: Any, root: str, step: int,
                    meta: Optional[dict] = None) -> str:
    """Atomically save `state` (+ JSON-serializable `meta`) as step `step`.

    Everything is written under a hidden `.tmp-step_N-<pid>` staging dir
    first; the final `os.replace` onto `step_N` is the commit point.  A
    directory named `step_N` therefore always holds a complete save.
    """
    root = os.path.abspath(root)
    os.makedirs(root, exist_ok=True)
    final = checkpoint_path(root, step)
    tmp = os.path.join(root, f"{_TMP_PREFIX}step_{step}-{os.getpid()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        save_sharded(state, os.path.join(tmp, _STATE_NAME))
        with open(os.path.join(tmp, _META_NAME), "w") as f:
            json.dump({"step": step, **(meta or {})}, f)
        if os.path.exists(final):  # e.g. re-save after a rolled-back restart
            shutil.rmtree(final)
        os.replace(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return final


def _complete_steps(root: str) -> list:
    """(step, path) for every COMPLETE checkpoint under root, ascending."""
    root = os.path.abspath(root)
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _STEP_DIR_RE.match(name)
        if not m:
            continue
        path = os.path.join(root, name)
        # The rename is the commit point, but guard against a partially
        # rm'd directory anyway: meta.json + state dir must both exist.
        if (os.path.isfile(os.path.join(path, _META_NAME))
                and os.path.isdir(os.path.join(path, _STATE_NAME))):
            out.append((int(m.group(1)), path))
    out.sort()
    return out


def latest_checkpoint(root: str) -> Optional[str]:
    """Path of the newest *complete* checkpoint under `root`, or None.

    In-progress / torn `.tmp-*` staging dirs and step dirs missing their
    meta or state are ignored — this is what the learner restart path
    resolves, so a crash mid-save can never be resumed from.
    """
    steps = _complete_steps(root)
    return steps[-1][1] if steps else None


def load_checkpoint(path: str, target: Any) -> Tuple[Any, dict]:
    """Restore (state, meta) from a complete checkpoint directory."""
    with open(os.path.join(path, _META_NAME)) as f:
        meta = json.load(f)
    state = restore_sharded(os.path.join(path, _STATE_NAME), target)
    return state, meta


def gc_checkpoints(root: str, keep: int) -> list:
    """Keep the newest `keep` complete checkpoints; delete the rest plus
    any torn `.tmp-*` staging dirs.  Returns the deleted paths."""
    root = os.path.abspath(root)
    deleted = []
    steps = _complete_steps(root)
    for _, path in steps[:-keep] if keep > 0 else steps:
        shutil.rmtree(path, ignore_errors=True)
        deleted.append(path)
    if os.path.isdir(root):
        for name in os.listdir(root):
            if name.startswith(_TMP_PREFIX):
                path = os.path.join(root, name)
                shutil.rmtree(path, ignore_errors=True)
                deleted.append(path)
    if deleted:
        logger.info("checkpoint GC removed %d dirs under %s",
                    len(deleted), root)
    return deleted
