"""Predictor + BatchPredictor: checkpoint-to-inference bridge.

Mirrors the reference's `python/ray/train/predictor.py` and
`batch_predictor.py`: a `Predictor` wraps model state restored from an
AIR `Checkpoint` and maps input batches to prediction batches; a
`BatchPredictor` scales that over a `Datastream` with a pool of predictor
actors (the reference uses `Datastream.map_batches(..., compute=actors)`).

TPU-first: `JaxPredictor.predict` runs a jitted apply function, so batch
inference on-chip is one compiled call per block.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint


class Predictor:
    """Base predictor: subclass with `_predict_numpy` or pass `predict_fn`."""

    def __init__(self, predict_fn: Optional[Callable] = None):
        self._predict_fn = predict_fn

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        if self._predict_fn is None:
            raise NotImplementedError
        return self._predict_fn(batch)


class JaxPredictor(Predictor):
    """Applies `apply_fn(params, batch) -> predictions` under jit, with
    params restored from a checkpoint dict (key 'params' by convention,
    matching train.step's checkpointing)."""

    def __init__(self, params: Any, apply_fn: Callable):
        super().__init__()
        import jax

        self._params = params
        self._apply = jax.jit(apply_fn)

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Callable) -> "JaxPredictor":
        data = checkpoint.to_dict()
        params = data.get("params", data)
        return cls(params, apply_fn)

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        import jax

        out = self._apply(self._params, batch)
        if not isinstance(out, dict):
            out = {"predictions": out}
        return {k: np.asarray(v) for k, v in jax.device_get(out).items()}


@ray_tpu.remote
class _PredictorActor:
    def __init__(self, predictor_cls, checkpoint: Checkpoint, kwargs: dict):
        self._predictor = predictor_cls.from_checkpoint(checkpoint, **kwargs)

    def predict(self, block) -> Any:
        if isinstance(block, dict):
            return self._predictor.predict(block)
        if not block:  # empty partition
            return []
        # row-list blocks: predict per row dict-of-scalars via a stacked batch
        batch = {k: np.asarray([r[k] for r in block]) for k in block[0]}
        out = self._predictor.predict(batch)
        n = len(block)
        return [{k: v[i] for k, v in out.items()} for i in range(n)]


class BatchPredictor:
    """Distributed inference over a Datastream
    (reference `batch_predictor.py`)."""

    def __init__(self, checkpoint: Checkpoint, predictor_cls,
                 **predictor_kwargs):
        self._checkpoint = checkpoint
        self._cls = predictor_cls
        self._kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, predictor_cls,
                        **kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **kwargs)

    def predict(self, data, *, num_actors: int = 2,
                resources_per_actor: Optional[Dict[str, float]] = None):
        """Map every block of `data` (Datastream) through predictor actors;
        returns a new Datastream of prediction blocks."""
        from ray_tpu.data.datastream import Datastream

        opts: Dict[str, Any] = {}
        if resources_per_actor:
            opts["resources"] = dict(resources_per_actor)
        else:
            opts["num_cpus"] = 1
        actors = [
            _PredictorActor.options(**opts).remote(
                self._cls, self._checkpoint, self._kwargs)
            for _ in range(num_actors)]
        try:
            refs = data._executed_refs()
            out_refs = []
            for i, ref in enumerate(refs):
                actor = actors[i % num_actors]
                out_refs.append(actor.predict.remote(ref))
            blocks = ray_tpu.get(out_refs)
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
        return Datastream([ray_tpu.put(b) for b in blocks])
