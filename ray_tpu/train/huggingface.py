"""TransformersTrainer: HuggingFace transformers.Trainer on the worker group.

Reference parity: python/ray/train/huggingface/huggingface_trainer.py — the
user supplies `trainer_init_per_worker(train_dataset, eval_dataset,
**config) -> transformers.Trainer`; each worker actor joins the torch gloo
process group (TorchTrainer machinery), materializes its Datastream shard
as a torch Dataset, builds the HF Trainer (HF's own code then drives DDP),
and a reporting callback forwards HF logs to `session.report` so Tune
schedulers see them. Rank 0 checkpoints the model state_dict at the end.

The accelerator path in this framework is JAX (`JaxTrainer`); this exists —
like TorchTrainer — so reference users' HF fine-tuning scripts port over
unchanged on CPU hosts.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.air import Checkpoint
from ray_tpu.air import session as air_session
from ray_tpu.train.torch import TorchConfig, TorchTrainer


def _shard_to_torch_dataset(shard):
    """Materialize a DataIterator / Datastream shard into an in-memory
    torch map-style dataset of row dicts."""
    if shard is None:
        return None
    import torch.utils.data as tud

    rows = list(shard.iter_rows())

    class _RowsDataset(tud.Dataset):
        def __len__(self):
            return len(rows)

        def __getitem__(self, i):
            return rows[i]

    return _RowsDataset()


def _make_loop(trainer_init_per_worker: Callable):
    def loop(config: Dict[str, Any]):
        import transformers

        train_ds = _shard_to_torch_dataset(
            air_session.get_dataset_shard("train"))
        eval_ds = _shard_to_torch_dataset(
            air_session.get_dataset_shard("evaluation"))
        hf_trainer = trainer_init_per_worker(train_ds, eval_ds, **config)

        class _ReportCallback(transformers.TrainerCallback):
            def on_log(self, args, state, control, logs=None, **kwargs):
                if logs:
                    air_session.report(
                        {**logs, "step": state.global_step,
                         "epoch": state.epoch})

        hf_trainer.add_callback(_ReportCallback())
        result = hf_trainer.train()
        final = dict(result.metrics or {})
        ckpt = None
        if air_session.get_world_rank() == 0:
            model = hf_trainer.model
            # unwrap DDP if HF wrapped it
            state_dict = getattr(model, "module", model).state_dict()
            ckpt = Checkpoint.from_dict({
                "state_dict": {k: v.cpu().numpy()
                               for k, v in state_dict.items()},
            })
        air_session.report(final, checkpoint=ckpt)

    return loop


class TransformersTrainer(TorchTrainer):
    """(reference `HuggingFaceTrainer`, huggingface_trainer.py)."""

    def __init__(self, trainer_init_per_worker: Callable, *,
                 trainer_init_config: Optional[Dict[str, Any]] = None,
                 torch_config: Optional[TorchConfig] = None, **kwargs):
        super().__init__(
            _make_loop(trainer_init_per_worker),
            train_loop_config=trainer_init_config,
            torch_config=torch_config, **kwargs)
