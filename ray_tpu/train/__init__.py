from ray_tpu.train.step import TrainState, make_train_step, make_init_fn, batch_sharding
from ray_tpu.train.predictor import BatchPredictor, JaxPredictor, Predictor
from ray_tpu.train.trainer import DataParallelTrainer, JaxTrainer
from ray_tpu.train.checkpointing import (abstract_like, gc_checkpoints,
                                         latest_checkpoint, load_checkpoint,
                                         restore_sharded, save_checkpoint,
                                         save_sharded)
from ray_tpu.train.sklearn import SklearnPredictor, SklearnTrainer
from ray_tpu.train.huggingface import TransformersTrainer
from ray_tpu.train.gbdt import (GBDTPredictor, GBDTTrainer, LightGBMTrainer,
                                LightGBMPredictor, XGBoostPredictor,
                                XGBoostTrainer)
