"""TorchTrainer: torch.distributed DDP over the actor worker group.

Mirrors the reference's Torch backend (`python/ray/train/torch/config.py:29`,
`_setup_torch_process_group:69` and `train_loop_utils.py prepare_model/
prepare_data_loader`): the trainer reserves a rendezvous port, every worker
actor joins a gloo process group before the user loop runs, and
`prepare_model`/`prepare_data_loader` wrap the user's module/loader in DDP +
DistributedSampler. gloo (CPU) is the backend — on this framework the TPU
compute path is JAX (`JaxTrainer`); TorchTrainer exists so reference users'
torch training code ports over unchanged.
"""

from __future__ import annotations

import logging
import socket
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from ray_tpu.air import session as air_session
from ray_tpu.train.trainer import DataParallelTrainer, _takes_arg

logger = logging.getLogger(__name__)

_MASTER_KEY = "_torch_master_addr"


@dataclass
class TorchConfig:
    backend: str = "gloo"
    init_timeout_s: float = 120.0


def get_device():
    import torch

    return torch.device("cpu")


def prepare_model(model):
    """Wrap in DDP when world_size > 1 (reference train_loop_utils.py:25)."""
    import torch.distributed as dist

    if dist.is_initialized() and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


def prepare_data_loader(loader):
    """Re-batch with a DistributedSampler so each rank sees its shard."""
    import torch.distributed as dist
    import torch.utils.data as tud

    if not (dist.is_initialized() and dist.get_world_size() > 1):
        return loader
    sampler = tud.distributed.DistributedSampler(loader.dataset)
    return tud.DataLoader(loader.dataset, batch_size=loader.batch_size,
                          sampler=sampler, num_workers=0,
                          collate_fn=loader.collate_fn)


def _wrap_with_process_group(train_loop: Callable, torch_config: TorchConfig):
    def wrapped(config: Dict[str, Any]):
        import datetime

        import torch.distributed as dist

        addr = config.pop(_MASTER_KEY)
        rank = air_session.get_world_rank()
        world = air_session.get_world_size()
        dist.init_process_group(
            torch_config.backend, init_method=f"tcp://{addr}",
            rank=rank, world_size=world,
            timeout=datetime.timedelta(seconds=torch_config.init_timeout_s))
        try:
            train_loop(config) if _takes_arg(train_loop) else train_loop()
        finally:
            dist.destroy_process_group()

    return wrapped


class TorchTrainer(DataParallelTrainer):
    """(reference `python/ray/train/torch/torch_trainer.py`)."""

    def __init__(self, train_loop_per_worker: Callable, *,
                 torch_config: Optional[TorchConfig] = None, **kwargs):
        self._torch_config = torch_config or TorchConfig()
        super().__init__(
            _wrap_with_process_group(train_loop_per_worker,
                                     self._torch_config),
            **kwargs)

    def _fit_once(self, checkpoint):
        # fresh rendezvous address per attempt (reference config.py:69 picks
        # a port on the rank-0 node; workers here share this host)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        self._config[_MASTER_KEY] = f"127.0.0.1:{port}"
        return super()._fit_once(checkpoint)
