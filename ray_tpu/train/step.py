"""Sharded training step: the pjit'd heart of the Train stack.

Where the reference's TorchTrainer wraps user loops around torch DDP/FSDP
(`python/ray/train/torch/config.py:69`, `train_loop_utils.py:92-101`), the
TPU-native step is one jitted function whose parallelism is entirely in the
in/out shardings: dp×fsdp shard the batch, fsdp shards parameters ZeRO-3
style (XLA inserts the all-gathers/reduce-scatters), tp shards heads/mlp,
sp runs ring attention. No collective calls appear below — the compiler
emits them over ICI/DCN from the sharding annotations.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models.transformer import (
    ModelConfig,
    init_params,
    loss_fn,
    param_logical_axes,
)
from ray_tpu.parallel.mesh import AxisRules, DEFAULT_RULES, logical_sharding


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


def default_optimizer(learning_rate: float = 3e-4,
                      weight_decay: float = 0.1,
                      warmup_steps: int = 100,
                      total_steps: int = 10000) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay,
                    mu_dtype=jnp.float32),
    )


def fused_adamw_optimizer(learning_rate: float = 3e-4,
                          weight_decay: float = 0.1,
                          warmup_steps: int = 100,
                          total_steps: int = 10000):
    """default_optimizer's schedule + hyperparams with the fused Pallas
    AdamW+clip apply (one memory pass over params/grads/moments)."""
    from ray_tpu.ops.pallas.adamw import FusedAdamW

    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return FusedAdamW(sched, b1=0.9, b2=0.95, weight_decay=weight_decay,
                      clip_norm=1.0)


def state_shardings(cfg: ModelConfig, mesh: Mesh,
                    optimizer: optax.GradientTransformation,
                    rules: AxisRules = DEFAULT_RULES) -> TrainState:
    """Build a TrainState of NamedShardings (same tree shape as the state)."""
    p_axes = param_logical_axes(cfg)
    p_sh = logical_sharding(mesh, p_axes, rules)
    params_shape = jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))
    opt_shape = jax.eval_shape(optimizer.init, params_shape)
    replicated = NamedSharding(mesh, P())
    opt_sh = _shard_opt_like_params(opt_shape, params_shape, p_sh, replicated)
    return TrainState(params=p_sh, opt_state=opt_sh, step=replicated)


def _shard_opt_like_params(opt_shape, params_shape, p_sh, replicated):
    """Optimizer states embed param-shaped subtrees (adam mu/nu); shard those
    like the params and replicate everything else (counts, schedules)."""
    param_struct = jax.tree_util.tree_structure(params_shape)

    def recurse(node):
        try:
            struct = jax.tree_util.tree_structure(node)
        except Exception:
            struct = None
        if struct == param_struct:
            return p_sh
        if isinstance(node, (list, tuple)):
            mapped = [recurse(x) for x in node]
            return type(node)(mapped) if not hasattr(node, "_fields") else type(node)(*mapped)
        if isinstance(node, dict):
            return {k: recurse(v) for k, v in node.items()}
        if dataclasses.is_dataclass(node) and not isinstance(node, jax.ShapeDtypeStruct):
            return type(node)(**{f.name: recurse(getattr(node, f.name))
                                 for f in dataclasses.fields(node)})
        return replicated

    return recurse(opt_shape)


def batch_sharding(mesh: Mesh) -> Dict[str, NamedSharding]:
    """inputs/targets [b, s]: batch over (dp, fsdp), seq over sp."""
    s = NamedSharding(mesh, P(("dp", "fsdp"), "sp"))
    return {"inputs": s, "targets": s}


def make_init_fn(cfg: ModelConfig, mesh: Mesh,
                 optimizer: optax.GradientTransformation,
                 rules: AxisRules = DEFAULT_RULES) -> Callable[[jax.Array], TrainState]:
    """Jitted, sharded-out initializer: params materialize directly on the
    mesh (an 8B model never exists unsharded on any host)."""
    sh = state_shardings(cfg, mesh, optimizer, rules)

    def init(rng):
        params = init_params(rng, cfg)
        return TrainState(params=params, opt_state=optimizer.init(params),
                          step=jnp.zeros((), jnp.int32))

    return jax.jit(init, out_shardings=sh)


def make_train_step(cfg: ModelConfig, mesh: Mesh,
                    optimizer: Optional[Any] = None,
                    rules: AxisRules = DEFAULT_RULES,
                    donate: bool = True):
    """Returns (step_fn, init_fn, shardings). step_fn(state, batch) ->
    (state, metrics); fully compiled, parameters donated.

    `optimizer` is an optax GradientTransformation, or a fused-apply
    optimizer (`ops.pallas.adamw.FusedAdamW`-style: `.apply(grads, state,
    params) -> (new_params, new_state)`) that updates params in one memory
    pass instead of returning deltas."""
    optimizer = optimizer or default_optimizer()
    fused = hasattr(optimizer, "apply")
    sh = state_shardings(cfg, mesh, optimizer, rules)
    b_sh = batch_sharding(mesh)

    def step(state: TrainState, batch: Dict[str, jax.Array]):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch, cfg, mesh)
        if fused:
            new_params, new_opt = optimizer.apply(grads, state.opt_state,
                                                  state.params)
        else:
            updates, new_opt = optimizer.update(grads, state.opt_state,
                                                state.params)
            new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "step": state.step,
        }
        return TrainState(new_params, new_opt, state.step + 1), metrics

    step_fn = jax.jit(
        step,
        in_shardings=(sh, b_sh),
        out_shardings=(sh, NamedSharding(mesh, P())),
        donate_argnums=(0,) if donate else (),
    )
    return step_fn, make_init_fn(cfg, mesh, optimizer, rules), sh
