"""Serialization: cloudpickle + out-of-band zero-copy buffers.

Equivalent role to the reference's `python/ray/_private/serialization.py:108`
(SerializationContext): cloudpickle for arbitrary Python, pickle protocol 5
out-of-band buffers so numpy / JAX host arrays are serialized as raw memory
views that can be written straight into (and read straight out of) the
shared-memory object store without copies.

Also tracks ObjectRefs nested inside serialized values so the ownership layer
can register borrows (cf. reference `AddNestedObjectIds`,
`src/ray/core_worker/reference_count.h:365`).
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, List, Tuple

import cloudpickle


class SerializedObject:
    """A serialized value: a small pickle payload + big zero-copy buffers."""

    __slots__ = ("payload", "buffers", "contained_refs")

    def __init__(self, payload: bytes, buffers: List[memoryview], contained_refs: list):
        self.payload = payload
        self.buffers = buffers
        self.contained_refs = contained_refs

    @property
    def total_bytes(self) -> int:
        return len(self.payload) + sum(b.nbytes for b in self.buffers)

    def to_bytes(self) -> bytes:
        """Flatten into one buffer: [n_bufs][len payload][payload][len b_i][b_i]..."""
        parts = [len(self.buffers).to_bytes(4, "big"), len(self.payload).to_bytes(8, "big"), self.payload]
        for b in self.buffers:
            parts.append(b.nbytes.to_bytes(8, "big"))
            parts.append(b)
        return b"".join(parts)

    def write_into(self, dst: memoryview) -> int:
        """Write the flattened representation into `dst`; returns bytes written."""
        off = 0

        def w(b):
            nonlocal off
            n = len(b) if isinstance(b, (bytes, bytearray)) else b.nbytes
            dst[off : off + n] = b
            off += n

        w(len(self.buffers).to_bytes(4, "big"))
        w(len(self.payload).to_bytes(8, "big"))
        w(self.payload)
        for b in self.buffers:
            w(b.nbytes.to_bytes(8, "big"))
            w(b)
        return off

    @classmethod
    def from_buffer(cls, src: memoryview) -> "SerializedObject":
        """Reconstruct (zero-copy: buffers are views into `src`)."""
        off = 0
        n_bufs = int.from_bytes(src[off : off + 4], "big")
        off += 4
        plen = int.from_bytes(src[off : off + 8], "big")
        off += 8
        payload = bytes(src[off : off + plen])
        off += plen
        buffers = []
        for _ in range(n_bufs):
            blen = int.from_bytes(src[off : off + 8], "big")
            off += 8
            buffers.append(src[off : off + blen])
            off += blen
        return cls(payload, buffers, [])


# Track refs encountered while pickling, via ObjectRef.__reduce__ hook.
_thread_local = threading.local()


def record_contained_ref(ref) -> None:
    refs = getattr(_thread_local, "contained_refs", None)
    if refs is not None:
        refs.append(ref)


def serialize(value: Any) -> SerializedObject:
    _thread_local.contained_refs = []
    buffers: List[pickle.PickleBuffer] = []
    try:
        payload = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
        contained = list(_thread_local.contained_refs)
    finally:
        _thread_local.contained_refs = None
    views = [b.raw() for b in buffers]
    return SerializedObject(payload, views, contained)


def deserialize(obj: SerializedObject) -> Any:
    return pickle.loads(obj.payload, buffers=obj.buffers)


def dumps(value: Any) -> bytes:
    """Convenience: serialize to a single contiguous bytes blob."""
    return serialize(value).to_bytes()


def loads(data: bytes | memoryview) -> Any:
    return deserialize(SerializedObject.from_buffer(memoryview(data)))
