"""Serialization: cloudpickle + out-of-band zero-copy buffers.

Equivalent role to the reference's `python/ray/_private/serialization.py:108`
(SerializationContext): cloudpickle for arbitrary Python, pickle protocol 5
out-of-band buffers so numpy / JAX host arrays are serialized as raw memory
views that can be written straight into (and read straight out of) the
shared-memory object store without copies.

Also tracks ObjectRefs nested inside serialized values so the ownership layer
can register borrows (cf. reference `AddNestedObjectIds`,
`src/ray/core_worker/reference_count.h:365`).
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, List, Tuple

import cloudpickle


class SerializedObject:
    """A serialized value: a small pickle payload + big zero-copy buffers.

    `payload` may be bytes OR a memoryview into a larger frame (the
    from-view read path keeps it a view so the error/inline/plasma decode
    paths never materialize an intermediate copy; pickle.loads accepts
    buffers directly)."""

    __slots__ = ("payload", "buffers", "contained_refs")

    def __init__(self, payload, buffers: List[memoryview], contained_refs: list):
        self.payload = payload
        self.buffers = buffers
        self.contained_refs = contained_refs

    # Buffers at least this large are 64-byte aligned within the flattened
    # frame: a misaligned destination halves memcpy bandwidth (measured
    # 5.2 vs 9.7 GB/s for a 100 MB copy at offset 12 vs 64). The padding is
    # DERIVED from the running offset on both the write and read side, so
    # the frame needs no extra fields.
    _ALIGN = 64
    _ALIGN_MIN = 2048

    @classmethod
    def _pad(cls, off: int, blen: int) -> int:
        if blen < cls._ALIGN_MIN:
            return 0
        return (-off) % cls._ALIGN

    @property
    def total_bytes(self) -> int:
        return len(self.payload) + sum(b.nbytes for b in self.buffers)

    @property
    def framed_size(self) -> int:
        """Exact byte length of the flattened frame (headers + alignment
        padding included) — what to_bytes/write_into/write_to_fd produce
        and what a store segment must hold."""
        off = 12 + len(self.payload)
        for b in self.buffers:
            off += 8
            off += self._pad(off, b.nbytes) + b.nbytes
        return off

    def to_bytes(self) -> bytes:
        """Flatten into one buffer:
        [n_bufs][len payload][payload]([len b_i][pad][b_i])..."""
        parts = [len(self.buffers).to_bytes(4, "big"),
                 len(self.payload).to_bytes(8, "big"), self.payload]
        off = 12 + len(self.payload)
        for b in self.buffers:
            parts.append(b.nbytes.to_bytes(8, "big"))
            off += 8
            pad = self._pad(off, b.nbytes)
            if pad:
                parts.append(bytes(pad))
            parts.append(b)
            off += pad + b.nbytes
        return b"".join(parts)

    def write_into(self, dst: memoryview) -> int:
        """Write the flattened representation into `dst`; returns bytes written."""
        off = 0

        def w(b):
            nonlocal off
            n = len(b) if isinstance(b, (bytes, bytearray)) else b.nbytes
            dst[off : off + n] = b
            off += n

        w(len(self.buffers).to_bytes(4, "big"))
        w(len(self.payload).to_bytes(8, "big"))
        w(self.payload)
        for b in self.buffers:
            w(b.nbytes.to_bytes(8, "big"))
            pad = self._pad(off, b.nbytes)
            if pad:
                w(bytes(pad))
            w(b)
        return off

    def write_to_fd(self, fd: int) -> int:
        """Write the flattened representation straight into an open fd with
        os.writev — the buffer-protocol put fast path. Unlike write_into on
        a fresh mmap (which faults in zero-filled pages and then copies over
        them), full-page file writes populate fresh tmpfs pages directly, so
        a large put costs ONE memory pass instead of two. Returns bytes
        written."""
        iov: List[memoryview] = [
            memoryview(len(self.buffers).to_bytes(4, "big")),
            memoryview(len(self.payload).to_bytes(8, "big")),
            memoryview(self.payload).cast("B")
            if not isinstance(self.payload, (bytes, bytearray))
            else memoryview(self.payload),
        ]
        off = 12 + len(self.payload)
        for b in self.buffers:
            iov.append(memoryview(b.nbytes.to_bytes(8, "big")))
            off += 8
            pad = self._pad(off, b.nbytes)
            if pad:
                iov.append(memoryview(bytes(pad)))
            v = b if isinstance(b, memoryview) else memoryview(b)
            if v.format != "B" or v.ndim != 1:
                v = v.cast("B")
            iov.append(v)
            off += pad + b.nbytes
        total = 0
        while iov:
            n = os.writev(fd, iov[:1024])  # IOV_MAX bound
            total += n
            while iov and n >= len(iov[0]):
                n -= len(iov[0])
                iov.pop(0)
            if n:
                iov[0] = iov[0][n:]
        return total

    @classmethod
    def from_buffer(cls, src: memoryview) -> "SerializedObject":
        """Reconstruct WITHOUT copying: the payload and every buffer are
        views into `src`, so values deserialized from a shared-memory
        segment (or an RPC frame) alias it rather than re-materializing.
        Callers that need the payload to outlive `src` must copy it
        themselves."""
        off = 0
        n_bufs = int.from_bytes(src[off : off + 4], "big")
        off += 4
        plen = int.from_bytes(src[off : off + 8], "big")
        off += 8
        payload = src[off : off + plen]
        off += plen
        buffers = []
        for _ in range(n_bufs):
            blen = int.from_bytes(src[off : off + 8], "big")
            off += 8
            off += cls._pad(off, blen)
            buffers.append(src[off : off + blen])
            off += blen
        return cls(payload, buffers, [])


# Track refs encountered while pickling, via ObjectRef.__reduce__ hook.
_thread_local = threading.local()


def record_contained_ref(ref) -> None:
    refs = getattr(_thread_local, "contained_refs", None)
    if refs is not None:
        refs.append(ref)


# Raw bytes/bytearray at least this large ride the out-of-band buffer lane
# (below it, header overhead beats the copy saved; above it, an in-band
# blob costs one copy into the growing pickle stream plus one into the
# flattened frame, where the out-of-band lane costs zero).
OOB_BYTES_MIN = 64 * 1024


def _rebuild_oob_bytes(buf) -> bytes:
    # out-of-band: `buf` is the transport's memoryview (one copy back to
    # bytes); in-band fallback (a pickler running without buffer_callback):
    # already bytes
    return buf if type(buf) is bytes else bytes(buf)


def _rebuild_oob_bytearray(buf) -> bytearray:
    return bytearray(buf)


class _OOBBlob:
    """Pickles as an out-of-band `PickleBuffer` over the wrapped blob. The
    C pickler serializes `bytes`/`bytearray` inline BEFORE consulting
    `reducer_override` or the dispatch_table, so raw blobs can't be
    intercepted mid-graph — `serialize()` pre-wraps them instead, and the
    wrapper's reduce puts the blob on the same zero-copy buffer plane that
    numpy arrays already ride (`write_to_fd` vectors it straight into the
    shm segment; no copy through the pickle stream)."""

    __slots__ = ("blob",)

    def __init__(self, blob):
        self.blob = blob

    def __reduce_ex__(self, protocol):
        if type(self.blob) is bytearray:
            return (_rebuild_oob_bytearray, (pickle.PickleBuffer(self.blob),))
        return (_rebuild_oob_bytes, (pickle.PickleBuffer(self.blob),))


def _is_big_blob(v) -> bool:
    return type(v) in (bytes, bytearray) and len(v) >= OOB_BYTES_MIN


def _route_oob(value: Any) -> Any:
    """Wrap large raw `bytes`/`bytearray` so they serialize out of band.
    Covers the shapes serve payloads and rollout blobs actually take — a
    top-level blob, or blobs sitting directly inside an exact dict / list /
    tuple — with a shallow scan only (no recursive walk: serialize() is on
    the task-submit hot path and deep graphs keep the C pickler's speed)."""
    t = type(value)
    if t in (bytes, bytearray):
        return _OOBBlob(value) if len(value) >= OOB_BYTES_MIN else value
    if t is dict:
        if any(_is_big_blob(v) for v in value.values()):
            return {k: (_OOBBlob(v) if _is_big_blob(v) else v)
                    for k, v in value.items()}
    elif t in (list, tuple):
        if any(_is_big_blob(v) for v in value):
            return t(_OOBBlob(v) if _is_big_blob(v) else v for v in value)
    return value


def serialize(value: Any) -> SerializedObject:
    _thread_local.contained_refs = []
    buffers: List[pickle.PickleBuffer] = []
    try:
        payload = cloudpickle.dumps(_route_oob(value), protocol=5,
                                    buffer_callback=buffers.append)
        contained = list(_thread_local.contained_refs)
    finally:
        _thread_local.contained_refs = None
    views = [b.raw() for b in buffers]
    return SerializedObject(payload, views, contained)


def deserialize(obj: SerializedObject) -> Any:
    return pickle.loads(obj.payload, buffers=obj.buffers)


def dumps(value: Any) -> bytes:
    """Convenience: serialize to a single contiguous bytes blob."""
    return serialize(value).to_bytes()


def loads(data: bytes | memoryview) -> Any:
    """The shared from-view deserializer: error/inline blobs and shm
    segments all decode through here with NO intermediate bytes — payload
    and out-of-band buffers stay views into `data`, so large numpy/JAX
    host arrays in the value alias it (read-only when `data` is). The
    views keep `data`'s exporter alive via the buffer protocol."""
    return deserialize(SerializedObject.from_buffer(memoryview(data)))


loads_view = loads  # explicit name for zero-copy call sites
