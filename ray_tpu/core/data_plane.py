"""Bulk object-transfer data plane: a dedicated raw-TCP channel per raylet.

The control RPC layer (rpc.py) frames every message as a pickle through one
asyncio loop — fine for control traffic, hopeless for multi-GiB objects (the
reference splits these planes the same way: gRPC control vs. dedicated
ObjectManager chunk streams, `src/ray/object_manager/object_manager.h:117`).

This channel moves object bytes with the minimum copies Python allows:

- FETCH: the server writes straight out of the sealed shm segment with
  ``sendall(memoryview)`` (no serialization, no staging buffer) and the
  puller reads straight into its pre-created destination segment with
  ``recv_into(memoryview)`` — shm -> kernel -> shm.
- Pulls stripe chunks across several persistent connections (the kernel
  copies in parallel with Python-side bookkeeping; on real NICs multiple
  streams also beat one TCP window).
- PUSH: source-initiated transfer for owner-directed broadcast (reference
  `push_manager.h:29`): the source streams an object into a peer's store
  unasked, so N readers don't serialize on one source; the receiver
  registers the new copy with the owner.

Protocol (all integers big-endian):
  request  = op:u8  idlen:u16  offset:u64  length:u64  id:idlen bytes
             (op FETCH: offset/length select the slice;
              op PUSH:  offset unused, length = total object size,
              followed — after a GO reply — by `length` raw bytes)
  reply    = status:u8  length:u64   (+ `length` raw payload bytes for FETCH)
  status   = 0 OK/GO, 1 MISSING/ERROR, 2 SKIP (push target already has it)
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ObjectID

logger = logging.getLogger(__name__)


def fan_out(fns: List[Callable[[], None]],
            timeout: Optional[float] = None) -> List[str]:
    """Run callables concurrently on daemon threads and return collected
    error strings (exceptions and timeouts). One shared deadline across all
    joins — per-thread timeouts would compound to N*timeout wall clock.
    Single callable runs inline (no thread). Shared by the transfer fan-out
    sites (striped pulls, multi-target pushes, parallel file copies)."""
    errors: List[str] = []
    if len(fns) == 1:
        try:
            fns[0]()
        except Exception as e:
            errors.append(str(e))
        return errors

    def wrap(fn: Callable[[], None]) -> None:
        try:
            fn()
        except Exception as e:
            errors.append(str(e))

    threads = [threading.Thread(target=wrap, args=(fn,),
                                name="dp-fan-out", daemon=True)
               for fn in fns]
    for t in threads:
        t.start()
    deadline = None if timeout is None else time.monotonic() + timeout
    for t in threads:
        t.join(None if deadline is None
               else max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            errors.append("fan-out worker timed out")
    return errors

OP_FETCH, OP_PUSH = 1, 2
OK, MISSING, SKIP = 0, 1, 2

_REQ = struct.Struct("!BHQQ")
_REP = struct.Struct("!BQ")

# One recv_into syscall cap: large enough to amortize syscall cost, small
# enough to keep the GIL released in long kernel copies without starving
# other threads.
_IO_BLOCK = 4 << 20


def _recv_exactly_into(sock: socket.socket, view: memoryview) -> None:
    got = 0
    n = len(view)
    while got < n:
        r = sock.recv_into(view[got:got + min(_IO_BLOCK, n - got)])
        if r == 0:
            raise ConnectionError("data-plane peer closed mid-transfer")
        got += r


def _recv_exactly(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    _recv_exactly_into(sock, memoryview(buf))
    return bytes(buf)


class DataPlaneServer:
    """Serves FETCH/PUSH on a dedicated port, one thread per connection
    (bulk copies release the GIL; connection counts are small — raylets
    hold a few persistent streams per peer)."""

    def __init__(self, store, host: str = "127.0.0.1",
                 on_pushed: Optional[Callable[[ObjectID, dict], None]] = None):
        self._store = store
        self._on_pushed = on_pushed  # called after a pushed object seals
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        self.host = host
        self.port = self._sock.getsockname()[1]
        self._stopped = False
        self._thread = threading.Thread(
            target=self._accept_loop, name="data-plane-accept", daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="data-plane-conn", daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while True:
                hdr = _recv_exactly(conn, _REQ.size)
                op, idlen, offset, length = _REQ.unpack(hdr)
                oid = ObjectID(_recv_exactly(conn, idlen))
                if op == OP_FETCH:
                    self._serve_fetch(conn, oid, offset, length)
                elif op == OP_PUSH:
                    self._serve_push(conn, oid, length)
                else:
                    conn.sendall(_REP.pack(MISSING, 0))
        except (ConnectionError, struct.error, OSError):
            pass
        except Exception:
            if not self._stopped:
                logger.exception("data-plane connection failed")
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_fetch(self, conn: socket.socket, oid: ObjectID,
                     offset: int, length: int) -> None:
        # pinned for the whole stream: a spill mid-transfer would unlink
        # the segment under the send and force a restore per stripe
        with self._store.pinned_view(oid) as buf:
            if buf is None:
                conn.sendall(_REP.pack(MISSING, 0))
                return
            view = memoryview(buf.view)[offset:offset + length]
            conn.sendall(_REP.pack(OK, len(view)))
            # zero-copy source: sendall walks the shm mapping directly
            conn.sendall(view)

    def _serve_push(self, conn: socket.socket, oid: ObjectID,
                    size: int) -> None:
        """Receive a source-initiated copy straight into a new segment."""
        from ray_tpu.core.config import get_config

        if self._store.contains(oid):
            conn.sendall(_REP.pack(SKIP, 0))
            return
        try:
            # bounded wait for eviction/unpin headroom (own thread per
            # connection — blocking here stalls only this push); a store
            # still full after the window replies MISSING and the source
            # falls back / retries
            shm = self._store.create_blocking(
                oid, size, min(get_config().put_full_timeout_s, 5.0))
        except FileExistsError:
            conn.sendall(_REP.pack(SKIP, 0))
            return
        except Exception:
            conn.sendall(_REP.pack(MISSING, 0))
            return
        ok = False
        try:
            conn.sendall(_REP.pack(OK, 0))  # GO: stream the body
            _recv_exactly_into(conn, memoryview(shm.buf)[:size])
            ok = True
        finally:
            shm.close()
            if not ok:
                try:
                    self._store.delete(oid)
                except KeyError:
                    pass  # partial create already evicted
        self._store.seal(oid)
        conn.sendall(_REP.pack(OK, 0))  # DONE
        if self._on_pushed is not None:
            try:
                self._on_pushed(oid, {})
            except Exception:
                logger.exception("on_pushed callback failed")

    def stop(self) -> None:
        self._stopped = True
        try:
            self._sock.close()
        except OSError:
            pass


class DataPlaneClient:
    """One persistent data-plane connection (NOT thread-safe: each puller
    stripe / pusher owns its own)."""

    def __init__(self, address: str, connect_timeout: float = 10.0):
        from ray_tpu.core.config import get_config

        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=connect_timeout)
        # stall timeout rides the transfer config knob, not a hardcode
        self._sock.settimeout(get_config().object_transfer_chunk_timeout_s * 2)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.address = address

    def fetch_into(self, oid: ObjectID, offset: int, length: int,
                   dest: memoryview) -> bool:
        """Pull one slice straight into `dest` (a shm segment slice).
        Returns False if the peer no longer holds the object."""
        idb = oid.binary()
        self._sock.sendall(_REQ.pack(OP_FETCH, len(idb), offset, length) + idb)
        status, n = _REP.unpack(_recv_exactly(self._sock, _REP.size))
        if status != OK:
            return False
        if n != length:
            raise ConnectionError(
                f"data-plane fetch returned {n} bytes, wanted {length}")
        _recv_exactly_into(self._sock, dest[:length])
        return True

    def push_from(self, oid: ObjectID, src: memoryview) -> str:
        """Stream a whole object to the peer. Returns 'ok', 'skip' (peer
        already has it) or raises."""
        idb = oid.binary()
        self._sock.sendall(_REQ.pack(OP_PUSH, len(idb), 0, len(src)) + idb)
        status, _ = _REP.unpack(_recv_exactly(self._sock, _REP.size))
        if status == SKIP:
            return "skip"
        if status != OK:
            raise ConnectionError("data-plane push rejected")
        self._sock.sendall(src)
        status, _ = _REP.unpack(_recv_exactly(self._sock, _REP.size))
        if status != OK:
            raise ConnectionError("data-plane push failed at receiver")
        return "ok"

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class DataPlanePool:
    """Small per-target pool of DataPlaneClient connections, checked out by
    the striped pull workers (connections are persistent; stripes reuse
    them across pulls)."""

    def __init__(self):
        self._free: Dict[str, List[DataPlaneClient]] = {}
        self._lock = threading.Lock()

    def acquire(self, address: str) -> DataPlaneClient:
        with self._lock:
            free = self._free.get(address)
            if free:
                return free.pop()
        return DataPlaneClient(address)

    def release(self, client: DataPlaneClient, broken: bool = False) -> None:
        if broken:
            client.close()
            return
        with self._lock:
            self._free.setdefault(client.address, []).append(client)

    def close(self) -> None:
        with self._lock:
            clients = [c for lst in self._free.values() for c in lst]
            self._free.clear()
        for c in clients:
            c.close()
