"""ctypes binding for the C++ shared-memory arena (src/arena/arena.cpp).

Builds the shared library on demand with g++ (cached by source hash under
build/); callers fall back to the file-per-object store path when the
toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "src", "arena", "arena.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")

_lib = None
_lib_lock = threading.Lock()
_lib_failed = False


def _load_lib():
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            with open(_SRC, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()[:16]
            so_path = os.path.join(_BUILD_DIR, f"libarena-{digest}.so")
            if not os.path.exists(so_path):
                os.makedirs(_BUILD_DIR, exist_ok=True)
                tmp = so_path + f".tmp{os.getpid()}"
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC, "-lpthread"],
                    check=True, capture_output=True)
                os.replace(tmp, so_path)
            lib = ctypes.CDLL(so_path)
            lib.arena_create.restype = ctypes.c_void_p
            lib.arena_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
            lib.arena_attach.restype = ctypes.c_void_p
            lib.arena_attach.argtypes = [ctypes.c_char_p]
            lib.arena_alloc.restype = ctypes.c_uint64
            lib.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.arena_free.restype = ctypes.c_int
            lib.arena_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.arena_used.restype = ctypes.c_uint64
            lib.arena_used.argtypes = [ctypes.c_void_p]
            lib.arena_capacity.restype = ctypes.c_uint64
            lib.arena_capacity.argtypes = [ctypes.c_void_p]
            lib.arena_base.restype = ctypes.c_void_p
            lib.arena_base.argtypes = [ctypes.c_void_p]
            lib.arena_close.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception:
            logger.warning("arena C++ library unavailable; falling back to "
                           "file-per-object store", exc_info=True)
            _lib_failed = True
        return _lib


NIL = (1 << 64) - 1


class Arena:
    """One shared-memory arena (create in the store daemon, attach anywhere)."""

    def __init__(self, lib, handle, path: str):
        self._lib = lib
        self._handle = handle
        self.path = path
        base = lib.arena_base(handle)
        cap = lib.arena_capacity(handle)
        self._view = memoryview(
            (ctypes.c_ubyte * cap).from_address(base)).cast("B")

    @classmethod
    def create(cls, path: str, capacity: int) -> Optional["Arena"]:
        lib = _load_lib()
        if lib is None:
            return None
        handle = lib.arena_create(path.encode(), capacity)
        if not handle:
            return None
        return cls(lib, handle, path)

    @classmethod
    def attach(cls, path: str) -> Optional["Arena"]:
        lib = _load_lib()
        if lib is None:
            return None
        handle = lib.arena_attach(path.encode())
        if not handle:
            return None
        return cls(lib, handle, path)

    def alloc(self, size: int) -> Optional[int]:
        off = self._lib.arena_alloc(self._handle, size)
        return None if off == NIL else off

    def free(self, offset: int) -> bool:
        return self._lib.arena_free(self._handle, offset) == 0

    def view(self, offset: int, size: int) -> memoryview:
        return self._view[offset:offset + size]

    @property
    def used(self) -> int:
        return self._lib.arena_used(self._handle)

    @property
    def capacity(self) -> int:
        return self._lib.arena_capacity(self._handle)

    def close(self) -> None:
        try:
            self._view.release()
        except Exception:
            pass
        self._lib.arena_close(self._handle)

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


# per-process cache of attached arenas (consumers)
_attached: dict = {}
_attached_lock = threading.Lock()


def attached_arena(path: str) -> Optional[Arena]:
    with _attached_lock:
        a = _attached.get(path)
        if a is None:
            a = Arena.attach(path)
            if a is not None:
                _attached[path] = a
        return a
