"""Per-node shared-memory object store + per-process in-memory store.

Equivalent role to the reference's plasma store
(`src/ray/object_manager/plasma/store.h:55`): immutable objects in shared
memory, one store per node, zero-copy reads from any worker process on that
node, LRU eviction and disk spilling when over budget
(cf. `ray_config_def.h:557-599`).

Redesign rationale (deliberate, documented per SURVEY §2.1): instead of one
mmap'd dlmalloc arena with fd passing over a unix socket (`plasma/fling.cc`),
each object is a named POSIX shared-memory segment (a /dev/shm tmpfs file,
see `ShmSegment`), created by whichever process produces the object and
attached by name from any process on the node. The kernel plays
the role of the arena allocator; eviction/spilling policy stays in the store
daemon. This removes an entire custom allocator + fd-passing protocol while
keeping the zero-copy property that matters on TPU hosts: a worker maps the
segment and hands `jax.device_put` a numpy view with no host-side copy.

Two tiers, matching reference semantics (SURVEY appendix C):
  - objects <= max_direct_call_object_size (100 KiB) travel inline in RPC
    replies into the owner's in-process object table (worker.py) — no shm
    round-trip;
  - larger objects land in the node `SharedObjectStore`, and only their
    location travels on the wire.
"""

from __future__ import annotations

import errno
import logging
import mmap
import os
import shutil
import struct
import threading
import time
import zlib
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.core.config import get_config
from ray_tpu.core.exceptions import ObjectLostError, ObjectStoreFullError
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.serialization import SerializedObject

logger = logging.getLogger(__name__)

_SHM_DIR = "/dev/shm"

# ---------------------------------------------------------------------------
# Spill envelope (storage failure domain): every spilled object is framed
#   magic(4) version(1) pad(3) payload_len(8) crc32(4) | payload
# written to a tmp name and committed with fsync + os.replace, so a spill
# file either exists complete-and-verifiable or not at all. _restore
# verifies magic, length AND checksum before attaching; any mismatch
# (torn write that raced a crash, bit rot, truncation, missing file) marks
# that copy LOST — a typed outcome that routes into lineage reconstruction
# instead of a raw buffer error (cf. reference local_object_manager.h spill
# IO workers + ObjectLostError semantics).

SPILL_MAGIC = b"RTSP"
SPILL_VERSION = 1
_SPILL_HDR = struct.Struct("<4sB3xQI")
SPILL_HEADER_SIZE = _SPILL_HDR.size


class SpillCorruptionError(ObjectLostError):
    """A spilled copy failed envelope verification (short read, bad magic,
    checksum mismatch, missing file). The copy is gone; whether the OBJECT
    is lost depends on lineage — callers route into reconstruction. Carries
    `reason` ("missing"/"torn"/"corrupt"/"io") for observability."""

    def __init__(self, message: str, reason: str = "corrupt"):
        super().__init__(message)
        self.reason = reason


def spill_pack_header(payload) -> bytes:
    mv = memoryview(payload)
    if mv.format != "B" or mv.ndim != 1:
        mv = mv.cast("B")
    return _SPILL_HDR.pack(SPILL_MAGIC, SPILL_VERSION, mv.nbytes,
                           zlib.crc32(mv) & 0xFFFFFFFF)


def spill_read_verified(path: str, expect_size: Optional[int] = None) -> bytes:
    """Read + verify a spill envelope; returns the payload. Raises
    SpillCorruptionError on ANY defect (typed reason attached)."""
    try:
        with open(path, "rb") as f:
            hdr = f.read(SPILL_HEADER_SIZE)
            if len(hdr) < SPILL_HEADER_SIZE:
                raise SpillCorruptionError(
                    f"spill file {path}: short header "
                    f"({len(hdr)}/{SPILL_HEADER_SIZE} bytes)", reason="torn")
            magic, version, length, crc = _SPILL_HDR.unpack(hdr)
            if magic != SPILL_MAGIC or version != SPILL_VERSION:
                raise SpillCorruptionError(
                    f"spill file {path}: bad magic/version "
                    f"({magic!r} v{version})", reason="corrupt")
            if expect_size is not None and length != expect_size:
                raise SpillCorruptionError(
                    f"spill file {path}: envelope length {length} != "
                    f"expected {expect_size}", reason="corrupt")
            payload = f.read(length)
    except FileNotFoundError:
        raise SpillCorruptionError(
            f"spill file {path}: missing", reason="missing") from None
    except OSError as e:
        raise SpillCorruptionError(
            f"spill file {path}: read failed: {e}", reason="io") from e
    if len(payload) != length:
        raise SpillCorruptionError(
            f"spill file {path}: short payload ({len(payload)}/{length} "
            f"bytes)", reason="torn")
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise SpillCorruptionError(
            f"spill file {path}: checksum mismatch", reason="corrupt")
    return payload


def _fs_fault(site: str) -> Optional[str]:
    """Seeded filesystem fault injection at named storage-IO sites
    (rpc.FaultInjector `fs:<site>:<mode>` rules). None when uninjected."""
    from ray_tpu.core.rpc import fs_fault

    return fs_fault(site)


class ShmSegment:
    """A named shared-memory segment backed by a /dev/shm file.

    We deliberately bypass `multiprocessing.shared_memory`: its per-process
    resource tracker assumes single-process ownership and unlinks (or
    complains about) segments owned by the store daemon. A plain tmpfs file
    + mmap gives identical performance with explicit lifetime control —
    the store daemon alone unlinks.
    """

    def __init__(self, name: str, size: int, create: bool = False,
                 readonly: bool = False, file_size: Optional[int] = None):
        self.name = name
        path = os.path.join(_SHM_DIR, name)
        if readonly:
            fd = os.open(path, os.O_RDONLY)
        else:
            flags = os.O_RDWR | (os.O_CREAT | os.O_EXCL if create else 0)
            fd = os.open(path, flags, 0o600)
        try:
            if create:
                # file_size may exceed the mapped size: the store sizes
                # files to page-rounded buckets so the reuse pool can hand
                # a segment to any object in the same bucket
                os.ftruncate(fd, max(file_size or size, 1))
            if readonly:
                # PROT_READ mapping: every view (and every numpy array
                # reconstructed over one) is read-only — the aliasing
                # contract for zero-copy get()
                self._mmap = mmap.mmap(fd, max(size, 1),
                                       prot=mmap.PROT_READ)
            else:
                self._mmap = mmap.mmap(fd, max(size, 1))
        finally:
            os.close(fd)

    @property
    def buf(self) -> memoryview:
        return memoryview(self._mmap)

    def close(self) -> None:
        try:
            self._mmap.close()
        except (BufferError, ValueError):
            pass  # exported views still alive; kernel reclaims at unmap

    @staticmethod
    def unlink(name: str) -> None:
        try:
            os.unlink(os.path.join(_SHM_DIR, name))
        except FileNotFoundError:
            pass


class SharedBuffer:
    """A zero-copy view of an object living in a shared-memory segment."""

    def __init__(self, shm: ShmSegment, size: int):
        self._shm = shm
        self.view = shm.buf[:size]
        self.name = shm.name
        self.size = size

    def close(self):
        try:
            self.view.release()
        except Exception:
            pass
        self._shm.close()


class ArenaBuffer:
    """A zero-copy view of an object living inside the C++ shared arena."""

    def __init__(self, view: memoryview, name: str, size: int):
        self.view = view
        self.name = name
        self.size = size

    @property
    def buf(self) -> memoryview:  # writer-side API parity with ShmSegment
        return self.view

    def close(self):
        try:
            self.view.release()
        except Exception:
            pass


@dataclass
class _Entry:
    name: str           # shm segment name, or "@<arena_path>:<offset>"
    size: int
    sealed: bool = False
    spilled_path: Optional[str] = None
    pinned: int = 0     # pin count (live zero-copy reader views)
    doomed: bool = False  # deleted while pinned: unlink deferred to last unpin
    arena_offset: Optional[int] = None
    created_at: float = field(default_factory=time.monotonic)


class SharedObjectStore:
    """Node-local store daemon state: segment registry + eviction + spill.

    Thread-safe; lives inside the raylet process. Producer workers create and
    write segments directly (zero-copy path) and then `seal()` them here;
    consumer workers `get()` the segment name and attach read-only.
    """

    def __init__(self, capacity: Optional[int] = None, spill_dir: Optional[str] = None):
        cfg = get_config()
        self.capacity = capacity or cfg.object_store_memory
        self.spill_dir = spill_dir or os.path.join(cfg.session_dir_root, "spill", str(os.getpid()))
        # disk-full degradation ladder: a spill write that fails with
        # ENOSPC/EIO retries down this dir list under backoff; when EVERY
        # dir fails the store goes spill-degraded (stops spilling, puts
        # flip to backpressure) and a periodic probe self-heals it
        self.spill_dirs: List[str] = [self.spill_dir] + [
            os.path.join(d, str(os.getpid()))
            for d in cfg.object_spill_dirs.split(":") if d.strip()]
        self._spill_degraded = False
        self._degraded_since = 0.0
        self._last_probe = 0.0
        self._entries: "OrderedDict[ObjectID, _Entry]" = OrderedDict()  # LRU order
        self._lock = threading.RLock()
        # waiters for admission headroom (bounded put backpressure):
        # notified whenever bytes are freed or the degraded state heals
        self._space = threading.Condition(self._lock)
        self._used = 0
        self._pinned_bytes = 0  # bytes of entries with >=1 reader pin
        # storage failure-domain counters (mirrored into stats() and the
        # ray_tpu_object_* metrics): spilled/restored byte totals, spill
        # failures by reason, lost spilled copies, admission rejections,
        # pin-cap refusals, degraded transitions
        self.counters: Dict[str, Any] = {
            "spilled_bytes": 0, "restored_bytes": 0,
            "spill_failures": {}, "lost_spills": 0,
            "put_backpressure": 0, "pin_cap_refusals": 0,
            "degraded_enters": 0, "degraded_heals": 0,
        }
        try:
            from ray_tpu.util import metrics as _m

            self._m_spilled = _m.get_or_create(
                "counter", "ray_tpu_object_spilled_bytes_total",
                "Bytes spilled to disk (committed envelopes)")
            self._m_restored = _m.get_or_create(
                "counter", "ray_tpu_object_restored_bytes_total",
                "Bytes restored from spill (verified envelopes)")
            self._m_spill_fail = _m.get_or_create(
                "counter", "ray_tpu_object_spill_failures_total",
                "Spill/restore failures by reason",
                tag_keys=("reason",))
            self._m_pinned = _m.get_or_create(
                "gauge", "ray_tpu_object_pinned_bytes",
                "Bytes held by reader pins (excluded from eviction)")
        except Exception:  # metrics are never load-bearing
            self._m_spilled = self._m_restored = None
            self._m_spill_fail = self._m_pinned = None
        # Segment-reuse pool: deleted (unpinned, unspilled) file segments
        # park here instead of unlinking, bucketed by their page-rounded
        # file size. Reusing a segment hands the writer ALREADY-FAULTED
        # tmpfs pages — a large put costs one memcpy into hot pages
        # (~4-5x the fresh-page path, which pays allocation + zeroing).
        # Safe against stale readers because consumers confirm a pin of
        # the ObjectID (and the segment name it maps to) before trusting
        # an attached view; a recycled inode fails that confirmation.
        self._pool: Dict[int, list] = {}   # file_size -> [names]
        self._pool_bytes = 0
        # never let idle pooled segments crowd out live objects: the pool
        # is capped at a quarter of the store even when the knob is larger
        self._pool_cap = min(cfg.object_segment_pool_bytes,
                             self.capacity // 4)
        # unique per store instance: several raylets (and their stores) can
        # share one process in in-process test clusters
        self._prefix = f"rtpu-{os.getpid()}-{os.urandom(3).hex()}-"
        self._seq = 0
        # C++ arena for small objects: one mmap, sub-allocated (plasma's
        # dlmalloc-arena design); file-per-object remains the big-object path
        self.arena_threshold = 1 << 20  # 1 MiB
        self._arena = None
        try:
            from ray_tpu.core.arena import Arena

            arena_cap = max(64 << 20, min(self.capacity // 4, 512 << 20))
            self._arena = Arena.create(
                os.path.join(_SHM_DIR, f"{self._prefix}arena"), arena_cap)
        except Exception:
            logger.debug("arena unavailable", exc_info=True)

    # ---- producer API ----------------------------------------------------
    @staticmethod
    def _bucket(size: int) -> int:
        return (max(size, 1) + 4095) & ~4095  # page-rounded file size

    def create(self, object_id: ObjectID, size: int,
               info: Optional[dict] = None) -> ShmSegment:
        """Allocate a segment for `object_id`; caller writes then seals.
        `info`, when given, is filled with {"recycled": bool} so the writer
        can pick its write strategy (mmap memcpy into hot recycled pages vs
        writev into a fresh file).

        Admission is honest: when eviction + spilling + pool drain cannot
        make `size` fit under capacity (every evictable entry is pinned or
        unsealed, or the store is spill-degraded), this raises typed
        ObjectStoreFullError instead of silently overcommitting `_used`
        past capacity. Callers bound their own wait (`put_full_timeout_s`)
        on headroom before surfacing it."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None:
                if e.doomed and e.sealed:
                    # re-put of an object deleted while readers were still
                    # pinned (lineage re-execution): the immutable old copy
                    # IS the object — resurrect it instead of reallocating
                    e.doomed = False
                raise FileExistsError(f"object {object_id} already exists")
            self._maybe_evict(size)
            self._admit(size)
            if self._arena is not None and size <= self.arena_threshold:
                off = self._arena.alloc(size)
                if off is not None:
                    name = f"@{self._arena.path}:{off}"
                    self._entries[object_id] = _Entry(
                        name=name, size=size, arena_offset=off)
                    self._used += size
                    return ArenaBuffer(self._arena.view(off, size), name, size)
            shm, recycled = self._alloc_file_segment(size)
            if info is not None:
                info["recycled"] = recycled
            self._entries[object_id] = _Entry(name=shm.name, size=size)
            self._used += size
            return shm

    def _alloc_file_segment(self, size: int):
        """Caller holds _lock. Returns (ShmSegment, recycled)."""
        bucket = self._bucket(size)
        names = self._pool.get(bucket)
        while names:
            name = names.pop()
            self._pool_bytes -= bucket
            try:
                return ShmSegment(name, size), True
            except OSError:
                continue  # swept by an external cleaner; fall through
        shm = None
        for _ in range(1000):
            self._seq += 1
            name = f"{self._prefix}{self._seq}"
            try:
                shm = ShmSegment(name, size, create=True, file_size=bucket)
                break
            except FileExistsError:
                continue  # stale segment from a crashed prior run
        if shm is None:
            raise RuntimeError("could not allocate shm segment")
        return shm, False

    def seal(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                raise KeyError(f"object {object_id} not found")
            e.sealed = True
            self._entries.move_to_end(object_id)

    def put_bytes(self, object_id: ObjectID, data: bytes | memoryview,
                  timeout_s: float = 0.0) -> None:
        """Copy `data` in and seal. `timeout_s` > 0 waits bounded for
        eviction/unpin headroom before raising ObjectStoreFullError (the
        server-internal materialization paths: pulls, pushes)."""
        n = len(data) if isinstance(data, (bytes, bytearray)) else data.nbytes
        if timeout_s > 0:
            shm = self.create_blocking(object_id, n, timeout_s)
        else:
            shm = self.create(object_id, n)
        try:
            if shm.name.startswith("@"):
                shm.buf[:n] = data
            else:
                # fd write, not the mapping: populates tmpfs pages directly
                # instead of zero-faulting a fresh mapping first (and on a
                # recycled segment skips repopulating the page table)
                fd = os.open(os.path.join(_SHM_DIR, shm.name), os.O_WRONLY)
                try:
                    mv = memoryview(data)
                    if mv.format != "B" or mv.ndim != 1:
                        mv = mv.cast("B")
                    off = 0
                    while off < n:
                        off += os.write(fd, mv[off:])
                finally:
                    os.close(fd)
        finally:
            shm.close()
        self.seal(object_id)

    def adopt_local_copy(self, object_id: ObjectID, src_name: str,
                         size: int) -> bool:
        """Same-host 'transfer' fast path: both raylets share this host's
        /dev/shm, so materializing the object is a KERNEL-side file copy
        (copy_file_range, parallelized across ranges on multi-core hosts) —
        no sockets, no serialization, and no mmap fault-zeroing pass (file
        writes populate fresh tmpfs pages directly). This is the moral
        equivalent of the reference's same-node plasma sharing: one store
        per node means local consumers never stream bytes at all.

        Returns False (leaving no entry behind) if the source segment is
        not visible locally or vanished mid-copy; raises FileExistsError
        like create() if the object is already materializing here."""
        if src_name.startswith("@"):
            return False  # arena-resident (small) objects: not a shm file
        src_path = os.path.join(_SHM_DIR, src_name)
        try:
            if os.path.getsize(src_path) < size:
                return False
        except OSError:
            return False
        dst = self.create(object_id, size)  # may raise FileExistsError
        ok = False
        try:
            if not hasattr(dst, "name") or dst.name.startswith("@"):
                # landed in the arena: copy through the mapping
                with open(src_path, "rb") as f:
                    dst.buf[:size] = f.read(size)
                ok = True
                return True
            dst_path = os.path.join(_SHM_DIR, dst.name)
            sfd = os.open(src_path, os.O_RDONLY)
            try:
                dfd = os.open(dst_path, os.O_RDWR)
                try:
                    n_par = min(os.cpu_count() or 1, 4,
                                max(1, size // (64 << 20)))
                    ok = self._copy_ranges(sfd, dfd, size, n_par)
                finally:
                    os.close(dfd)
            finally:
                os.close(sfd)
            return ok
        finally:
            dst.close()
            if ok:
                self.seal(object_id)
            else:
                self.delete(object_id)

    @staticmethod
    def _copy_ranges(sfd: int, dfd: int, size: int, n_par: int) -> bool:
        def copy_range(off: int, end: int) -> None:
            while off < end:
                r = os.copy_file_range(sfd, dfd, end - off, off, off)
                if r == 0:
                    raise OSError("source segment truncated mid-copy")
                off += r

        from ray_tpu.core.data_plane import fan_out

        step = -(-size // max(1, n_par))
        errors = fan_out([lambda o=o: copy_range(o, min(o + step, size))
                          for o in range(0, size, step)])
        return not errors

    # ---- consumer API ----------------------------------------------------
    def status(self, object_id: ObjectID) -> Optional[str]:
        """"sealed" | "unsealed" | None (absent or deleted-while-pinned)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or e.doomed:
                return None
            return "sealed" if e.sealed else "unsealed"

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed and not e.doomed

    def lookup(self, object_id: ObjectID) -> Optional[tuple[str, int]]:
        """Return (segment_name, size) for a sealed object, restoring from
        spill if needed; None if absent (or deleted-but-pinned, or the
        spilled copy failed envelope verification — the entry is dropped
        and the caller's absent-handling routes into reconstruction)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed or e.doomed:
                return None
            if e.spilled_path is not None:
                try:
                    self._restore(object_id, e)
                except SpillCorruptionError:
                    return None  # copy LOST; _restore dropped the entry
            self._entries.move_to_end(object_id)
            return (e.name, e.size)

    # ---- pin protocol ----------------------------------------------------
    def pin(self, object_id: ObjectID,
            transient: bool = False) -> Optional[tuple[str, int]]:
        """Pin a sealed object for a zero-copy reader and return its
        CURRENT (segment_name, size); None if absent/unsealed/doomed (or
        the pin-cap refused — see pin_ex to distinguish).
        While pinned the entry is excluded from spill and eviction, and a
        delete() defers the unlink until the last unpin — so reader views
        into the segment stay valid (and accounted) for their lifetime.
        Restores from spill first: pinning declares intent to attach.

        Pin-cap accounting: indefinite reader pins (`transient=False`) may
        collectively hold at most `max_pinned_fraction` of capacity — the
        FIRST pin of an entry that would cross the cap is refused, so
        pinned entries can never wedge eviction entirely. `transient=True`
        (scoped reads: pinned_view, bounded copy windows) bypasses the cap
        — those pins are released within one operation."""
        loc, _ = self.pin_ex(object_id, transient=transient)
        return loc

    def pin_ex(self, object_id: ObjectID, transient: bool = False
               ) -> tuple[Optional[tuple[str, int]], Optional[str]]:
        """pin() with a reason channel: (loc, None) on success,
        (None, "absent" | "lost" | "pin_cap") on refusal. "pin_cap" means
        the object IS resident — the caller may fall back to a transient
        pin + bounded copy instead of treating it as gone."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed or e.doomed:
                return None, "absent"
            if e.spilled_path is not None:
                try:
                    self._restore(object_id, e)
                except SpillCorruptionError:
                    return None, "lost"  # copy LOST; entry dropped
            if (not transient and e.pinned == 0
                    and e.arena_offset is None
                    and self._pinned_bytes + e.size
                    > get_config().max_pinned_fraction * self.capacity):
                self.counters["pin_cap_refusals"] += 1
                return None, "pin_cap"
            if e.pinned == 0:
                self._pinned_bytes += e.size
                if self._m_pinned is not None:
                    self._m_pinned.set(self._pinned_bytes)
            e.pinned += 1
            self._entries.move_to_end(object_id)
            return (e.name, e.size), None

    def unpin(self, object_id: ObjectID) -> None:
        """Release one pin; finishes a deferred delete at the last one.
        Unknown ids are ignored (a reader's compensating unpin after a
        failed attach may race the owner's delete)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return
            was = e.pinned
            e.pinned = max(0, e.pinned - 1)
            if was == 1 and e.pinned == 0:
                self._pinned_bytes = max(0, self._pinned_bytes - e.size)
                if self._m_pinned is not None:
                    self._m_pinned.set(self._pinned_bytes)
                # newly unpinned bytes are spillable again: wake admission
                # waiters parked in create_blocking
                self._space.notify_all()
            if e.doomed and e.pinned == 0:
                self._entries.pop(object_id, None)
                if e.arena_offset is not None:
                    if self._arena is not None:
                        self._arena.free(e.arena_offset)
                    self._used -= e.size
                else:
                    self._reclaim(e)

    def get_buffer(self, object_id: ObjectID):
        """In-process zero-copy read (same process as the store). The
        buffer holds a PIN until close() — under the segment-reuse pool an
        unpinned attach would be unsafe (a concurrent delete could recycle
        and overwrite the inode beneath the view), so callers MUST close.
        Scoped readers should prefer pinned_view."""
        loc = self.pin(object_id, transient=True)
        if loc is None:
            return None
        try:
            buf = attach_object(*loc)
        except (FileNotFoundError, OSError):
            self.unpin(object_id)
            return None
        inner_close = buf.close
        released = []

        def close():
            if not released:
                released.append(True)
                inner_close()
                self.unpin(object_id)

        buf.close = close
        return buf

    @contextmanager
    def pinned_view(self, object_id: ObjectID):
        """Pin + attach + release in one scope: the shared from-view read
        used by every server-side consumer (data-plane fetch, RPC chunk
        serves). The pin keeps the segment out of spill/eviction for the
        duration, so a long transfer can't race a spill into a
        double-IO restore (or a recycled inode). Yields the buffer, or
        None when the object is absent (or its spilled copy is lost).
        Transient: scoped pins bypass the `max_pinned_fraction` cap."""
        loc = self.pin(object_id, transient=True)
        if loc is None:
            yield None
            return
        buf = None
        try:
            try:
                buf = attach_object(*loc, readonly=True)
            except (FileNotFoundError, OSError):
                yield None
                return
            yield buf
        finally:
            if buf is not None:
                buf.close()
            self.unpin(object_id)

    def read_bytes(self, object_id: ObjectID) -> Optional[bytes]:
        """Materializing read — ONLY for callers that need owned bytes
        (the wire). Consumers that immediately deserialize should use
        pinned_view + serialization.loads instead (no intermediate copy)."""
        with self.pinned_view(object_id) as buf:
            if buf is None:
                return None
            return bytes(buf.view)

    # ---- lifecycle -------------------------------------------------------
    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return
            if e.pinned > 0 and e.spilled_path is None:
                # zero-copy (or pinned_view) readers still hold views into
                # the segment / arena slot: hide the entry (lookup/contains
                # say gone) but defer the reclaim — the last unpin runs it
                e.doomed = True
                return
            self._entries.pop(object_id, None)
            if e.pinned > 0:
                # deleting a pinned-but-spilled entry: its pin bytes leave
                # the cap accounting with it
                self._pinned_bytes = max(0, self._pinned_bytes - e.size)
            if e.arena_offset is not None:
                if self._arena is not None:
                    self._arena.free(e.arena_offset)
                self._used -= e.size
                self._space.notify_all()
            elif e.spilled_path is None:
                self._reclaim(e)
            elif os.path.exists(e.spilled_path):
                try:
                    os.unlink(e.spilled_path)
                except OSError:
                    pass

    def _reclaim(self, e: _Entry) -> None:
        """Caller holds _lock. Retire a live file segment: park it in the
        reuse pool (pages stay hot for the next same-bucket create),
        evicting older pooled segments to make room — the workload's
        CURRENT object size wins the pool. Oversized segments unlink."""
        self._used -= e.size
        bucket = self._bucket(e.size)
        if bucket > self._pool_cap:
            self._unlink(e)
            return
        need = self._pool_bytes + bucket - self._pool_cap
        if need > 0:
            self._drain_pool(need)
        self._pool.setdefault(bucket, []).append(e.name)
        self._pool_bytes += bucket
        self._space.notify_all()  # freed live bytes: wake admission waiters

    def _drain_pool(self, want: int) -> int:
        """Caller holds _lock. Unlink pooled segments until `want` bytes
        are freed (memory pressure beats reuse warmth). Returns freed."""
        freed = 0
        for bucket in sorted(self._pool, reverse=True):
            names = self._pool[bucket]
            while names and freed < want:
                ShmSegment.unlink(names.pop())
                self._pool_bytes -= bucket
                freed += bucket
            if freed >= want:
                break
        return freed

    def stats(self) -> dict:
        with self._lock:
            spilled = sum(1 for e in self._entries.values() if e.spilled_path)
            spilled_bytes = sum(e.size for e in self._entries.values()
                                if e.spilled_path)
            pinned = sum(1 for e in self._entries.values() if e.pinned > 0)
            c = self.counters
            return {
                "num_objects": len(self._entries),
                "used_bytes": self._used,
                "capacity_bytes": self.capacity,
                "num_spilled": spilled,
                "spilled_bytes": spilled_bytes,
                "num_pinned": pinned,
                "pinned_refs": sum(e.pinned for e in self._entries.values()),
                "pinned_bytes": self._pinned_bytes,
                "pool_bytes": self._pool_bytes,
                "spill_degraded": self._spill_degraded,
                "spilled_bytes_total": c["spilled_bytes"],
                "restored_bytes_total": c["restored_bytes"],
                "spill_failures": dict(c["spill_failures"]),
                "lost_spills": c["lost_spills"],
                "put_backpressure": c["put_backpressure"],
                "pin_cap_refusals": c["pin_cap_refusals"],
                "degraded_enters": c["degraded_enters"],
                "degraded_heals": c["degraded_heals"],
            }

    def shutdown(self) -> None:
        with self._lock:
            for oid, e in list(self._entries.items()):
                e.pinned = 0  # process exiting: force-reclaim
                e.doomed = False
                self.delete(oid)
            self._pinned_bytes = 0
            self._drain_pool(self._pool_bytes)
            if self._arena is not None:
                self._arena.close()
                self._arena.unlink()
                self._arena = None

    # ---- internals -------------------------------------------------------
    def _unlink(self, e: _Entry) -> None:
        ShmSegment.unlink(e.name)

    def _admit(self, incoming: int) -> None:
        """Caller holds _lock, after _maybe_evict. Typed store-full check:
        live bytes + pooled segments + the incoming object must fit under
        capacity. The pool is drained first (idle warmth never causes a
        rejection); what remains over budget is genuine — pinned or
        unsealed entries that cannot move, or a spill-degraded store."""
        deficit = self._used + self._pool_bytes + incoming - self.capacity
        if deficit > 0:
            self._drain_pool(deficit)
        if self._used + self._pool_bytes + incoming <= self.capacity:
            return
        self.counters["put_backpressure"] += 1
        raise ObjectStoreFullError(
            f"object store cannot admit {incoming} bytes: "
            f"used={self._used} pinned={self._pinned_bytes} "
            f"pool={self._pool_bytes} capacity={self.capacity}"
            + (" [spill-degraded: every spill dir is failing]"
               if self._spill_degraded else ""))

    def create_blocking(self, object_id: ObjectID, size: int,
                        timeout_s: float, info: Optional[dict] = None):
        """create() with a bounded wait for eviction/unpin headroom: parks
        on the store's space condition (notified by delete/unpin/heal)
        until admission succeeds or `timeout_s` expires, then re-raises the
        typed ObjectStoreFullError. For server-internal materialization
        paths (pulls, data-plane pushes) that run on their own threads;
        the worker put path does its own client-side bounded retry."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._space:
            while True:
                try:
                    return self.create(object_id, size, info=info)
                except ObjectStoreFullError:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or size > self.capacity:
                        raise
                    self._space.wait(min(remaining, 0.1))

    def _maybe_evict(self, incoming: int) -> None:
        """Spill least-recently-used sealed objects until there is room.

        Mirrors the reference's threshold-triggered spilling
        (`object_spilling_threshold` 0.8, `ray_config_def.h:583`). A
        spill-degraded store (every spill dir failing) skips spilling
        entirely — after a probe-period attempt to self-heal — and only
        drains the pool; admission then backpressures puts.
        """
        threshold = get_config().object_spilling_threshold
        budget = self.capacity * threshold - self._pool_bytes
        if self._used + incoming <= budget:
            return
        # reclaim idle pooled segments before spilling LIVE objects: pool
        # warmth never costs a spill
        self._drain_pool(int(self._used + incoming - budget))
        budget = self.capacity * threshold - self._pool_bytes
        if self._spill_degraded and not self._probe_spill_dirs():
            return
        for oid in list(self._entries):
            if self._used + incoming <= budget:
                break
            e = self._entries[oid]
            if (not e.sealed or e.spilled_path is not None or e.pinned > 0
                    or e.arena_offset is not None):
                continue  # pinned entries hold reader views; arena objects
                # are small — only idle file segments spill
            if not self._spill(oid, e) and self._spill_degraded:
                return  # every dir just failed: stop burning IO this pass

    def _probe_spill_dirs(self) -> bool:
        """Caller holds _lock. Self-healing probe for the spill-degraded
        state: at most once per `spill_degraded_probe_period_s`, try a
        tiny committed write in each spill dir (through the same fault
        points as a real spill). One healthy dir clears degradation and
        wakes admission waiters. Returns the healthy/healed state."""
        if not self._spill_degraded:
            return True
        period = get_config().spill_degraded_probe_period_s
        now = time.monotonic()
        if period <= 0 or now - self._last_probe < period:
            return False
        self._last_probe = now
        for d in self.spill_dirs:
            try:
                if _fs_fault("spill_write") in ("enospc", "eio"):
                    continue  # injected window still open for this probe
                os.makedirs(d, exist_ok=True)
                probe = os.path.join(d, ".probe")
                with open(probe + ".tmp", "wb") as f:
                    f.write(b"rtpu-probe")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(probe + ".tmp", probe)
                os.unlink(probe)
            except OSError:
                continue
            self._spill_degraded = False
            self.counters["degraded_heals"] += 1
            logger.warning("object store spill path healed (dir %s); "
                           "resuming spilling", d)
            self._space.notify_all()
            return True
        return False

    def _count_spill_failure(self, reason: str) -> None:
        fails = self.counters["spill_failures"]
        fails[reason] = fails.get(reason, 0) + 1
        if self._m_spill_fail is not None:
            self._m_spill_fail.inc(tags={"reason": reason})

    def _spill(self, object_id: ObjectID, e: _Entry) -> bool:
        """Caller holds _lock. Durable spill: checksummed envelope, tmp
        write, fsync, os.replace — the file is either complete and
        verifiable or absent. ENOSPC/EIO retries down `spill_dirs` under
        backoff; when every dir fails the store enters the spill-degraded
        state (spilling stops, puts flip to backpressure) until a probe
        heals it. Returns True when the object moved to disk."""
        cfg = get_config()
        try:
            shm = ShmSegment(e.name, e.size)
        except FileNotFoundError:
            return False  # segment swept externally; nothing to spill
        try:
            payload = bytes(shm.buf[: e.size])
        finally:
            shm.close()
        header = spill_pack_header(payload)
        injected = _fs_fault("spill_write")
        if injected == "bitflip" and e.size > 0:
            # corrupt ONE payload byte after checksumming: the envelope
            # commits "successfully" and the defect is only caught by
            # _restore's verification — the silent-bit-rot scenario
            corrupt = bytearray(payload)
            corrupt[len(corrupt) // 2] ^= 0x40
            payload = bytes(corrupt)
        for d in self.spill_dirs:
            path = os.path.join(d, object_id.hex())
            tmp = path + ".tmp"
            for attempt in range(max(1, cfg.spill_write_retries)):
                try:
                    if injected in ("enospc", "eio"):
                        raise OSError(
                            errno.ENOSPC if injected == "enospc"
                            else errno.EIO,
                            f"[fault-injection] {injected} on spill_write")
                    os.makedirs(d, exist_ok=True)
                    with open(tmp, "wb") as f:
                        f.write(header)
                        if injected == "torn":
                            # commit a half-written payload: a crash that
                            # raced the write — caught by length/crc checks
                            f.write(payload[: max(0, e.size // 2)])
                        else:
                            f.write(payload)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, path)
                except OSError as err:
                    self._count_spill_failure(
                        "enospc" if err.errno == errno.ENOSPC else "io")
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    if attempt + 1 < max(1, cfg.spill_write_retries):
                        time.sleep(cfg.spill_retry_backoff_ms / 1000.0
                                   * (attempt + 1))
                    # next attempt re-rolls the injector: a probabilistic
                    # ENOSPC window can clear mid-retry like a real disk
                    injected = _fs_fault("spill_write")
                    continue
                self._unlink(e)
                e.spilled_path = path
                self._used -= e.size
                self.counters["spilled_bytes"] += e.size
                if self._m_spilled is not None:
                    self._m_spilled.inc(e.size)
                logger.debug("spilled %s (%d bytes) to %s",
                             object_id, e.size, path)
                return True
            injected = _fs_fault("spill_write")
        if not self._spill_degraded:
            self._spill_degraded = True
            self._degraded_since = time.monotonic()
            self._last_probe = time.monotonic()
            self.counters["degraded_enters"] += 1
            logger.error(
                "object store is SPILL-DEGRADED: every spill dir failed "
                "(%s); spilling stops and puts backpressure until a probe "
                "heals", self.spill_dirs)
        return False

    def _restore(self, object_id: ObjectID, e: _Entry) -> None:
        """Caller holds _lock. Verified restore: the envelope's magic,
        length and crc32 must all check out before the payload re-enters
        shm. ANY defect (torn, short, corrupt, missing, unreadable) marks
        this copy LOST — the entry is dropped and SpillCorruptionError
        (an ObjectLostError) raised; callers surface absent and lineage
        reconstruction takes over."""
        assert e.spilled_path is not None
        path = e.spilled_path
        try:
            injected = _fs_fault("spill_restore")
            if injected in ("eio", "torn"):
                raise SpillCorruptionError(
                    f"spill file {path}: [fault-injection] {injected} on "
                    f"restore", reason="torn" if injected == "torn"
                    else "io")
            payload = spill_read_verified(path, expect_size=e.size)
            if injected == "bitflip":
                raise SpillCorruptionError(
                    f"spill file {path}: [fault-injection] bitflip on "
                    f"restore", reason="corrupt")
        except SpillCorruptionError as err:
            # the copy is gone: drop the entry + the bad file so repeated
            # lookups don't re-verify a corpse, count it, surface typed
            self._entries.pop(object_id, None)
            try:
                os.unlink(path)
            except OSError:
                pass
            self.counters["lost_spills"] += 1
            self._count_spill_failure(err.reason)
            logger.error("spilled copy of %s LOST (%s): %s",
                         object_id, err.reason, err)
            raise
        self._maybe_evict(e.size)
        shm, _ = self._alloc_file_segment(e.size)
        name = shm.name
        try:
            shm.buf[: e.size] = payload
        finally:
            shm.close()
        try:
            os.unlink(path)
        except OSError:
            pass
        e.name = name
        e.spilled_path = None
        self._used += e.size
        self.counters["restored_bytes"] += e.size
        if self._m_restored is not None:
            self._m_restored.inc(e.size)
        logger.debug("restored %s from spill", object_id)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return False
    return True


def sweep_stale_spill_dirs(roots: Optional[List[str]] = None,
                           live_pids: Optional[set] = None) -> List[str]:
    """Collect spill dirs leaked by SIGKILLed stores. Spill dirs are keyed
    `<root>/<pid>`; a raylet that dies without shutdown() leaves its dir
    (and every spilled object in it) behind forever — every kill storm
    does this. Sweeps children whose pid no longer runs, mirroring the
    `rtpu-worker-*.env` reaper (raylet._sweep_stale_envfiles): called at
    store startup and hourly from the raylet reaper loop. Returns the
    removed paths. `roots` defaults to the session spill root plus every
    configured `object_spill_dirs` entry."""
    cfg = get_config()
    if roots is None:
        roots = [os.path.join(cfg.session_dir_root, "spill")] + [
            d for d in cfg.object_spill_dirs.split(":") if d.strip()]
    live = set(live_pids or ())
    live.add(os.getpid())
    removed: List[str] = []
    for root in roots:
        try:
            children = os.listdir(root)
        except OSError:
            continue
        for child in children:
            if not child.isdigit() or int(child) in live:
                continue
            if _pid_alive(int(child)):
                continue
            path = os.path.join(root, child)
            try:
                shutil.rmtree(path)
                removed.append(path)
            except OSError:
                pass  # raced another sweeper / permissions: next pass
    if removed:
        logger.info("reaped %d stale spill dir(s): %s",
                    len(removed), removed[:4])
    return removed


def attach_object(name: str, size: int, readonly: bool = False):
    """Attach to a sealed object from any process on the node.

    `name` is either a /dev/shm segment name or "@<arena_path>:<offset>"
    for objects living in the C++ shared arena. With `readonly` the
    mapping is PROT_READ, so every view (and numpy array over one) is
    immutable — the aliasing contract for zero-copy get().
    """
    if name.startswith("@"):
        from ray_tpu.core.arena import attached_arena

        path, off = name[1:].rsplit(":", 1)
        arena = attached_arena(path)
        if arena is None:
            raise FileNotFoundError(f"cannot attach arena {path}")
        return ArenaBuffer(arena.view(int(off), size), name, size)
    return SharedBuffer(ShmSegment(name, size, readonly=readonly), size)
