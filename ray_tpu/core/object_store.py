"""Per-node shared-memory object store + per-process in-memory store.

Equivalent role to the reference's plasma store
(`src/ray/object_manager/plasma/store.h:55`): immutable objects in shared
memory, one store per node, zero-copy reads from any worker process on that
node, LRU eviction and disk spilling when over budget
(cf. `ray_config_def.h:557-599`).

Redesign rationale (deliberate, documented per SURVEY §2.1): instead of one
mmap'd dlmalloc arena with fd passing over a unix socket (`plasma/fling.cc`),
each object is a named POSIX shared-memory segment (a /dev/shm tmpfs file,
see `ShmSegment`), created by whichever process produces the object and
attached by name from any process on the node. The kernel plays
the role of the arena allocator; eviction/spilling policy stays in the store
daemon. This removes an entire custom allocator + fd-passing protocol while
keeping the zero-copy property that matters on TPU hosts: a worker maps the
segment and hands `jax.device_put` a numpy view with no host-side copy.

Two tiers, matching reference semantics (SURVEY appendix C):
  - objects <= max_direct_call_object_size (100 KiB) travel inline in RPC
    replies into the owner's in-process object table (worker.py) — no shm
    round-trip;
  - larger objects land in the node `SharedObjectStore`, and only their
    location travels on the wire.
"""

from __future__ import annotations

import logging
import mmap
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.serialization import SerializedObject

logger = logging.getLogger(__name__)

_SHM_DIR = "/dev/shm"


class ShmSegment:
    """A named shared-memory segment backed by a /dev/shm file.

    We deliberately bypass `multiprocessing.shared_memory`: its per-process
    resource tracker assumes single-process ownership and unlinks (or
    complains about) segments owned by the store daemon. A plain tmpfs file
    + mmap gives identical performance with explicit lifetime control —
    the store daemon alone unlinks.
    """

    def __init__(self, name: str, size: int, create: bool = False):
        self.name = name
        path = os.path.join(_SHM_DIR, name)
        flags = os.O_RDWR | (os.O_CREAT | os.O_EXCL if create else 0)
        fd = os.open(path, flags, 0o600)
        try:
            if create:
                os.ftruncate(fd, max(size, 1))
            self._mmap = mmap.mmap(fd, max(size, 1))
        finally:
            os.close(fd)

    @property
    def buf(self) -> memoryview:
        return memoryview(self._mmap)

    def close(self) -> None:
        try:
            self._mmap.close()
        except (BufferError, ValueError):
            pass  # exported views still alive; kernel reclaims at unmap

    @staticmethod
    def unlink(name: str) -> None:
        try:
            os.unlink(os.path.join(_SHM_DIR, name))
        except FileNotFoundError:
            pass


class SharedBuffer:
    """A zero-copy view of an object living in a shared-memory segment."""

    def __init__(self, shm: ShmSegment, size: int):
        self._shm = shm
        self.view = shm.buf[:size]
        self.name = shm.name
        self.size = size

    def close(self):
        try:
            self.view.release()
        except Exception:
            pass
        self._shm.close()


class ArenaBuffer:
    """A zero-copy view of an object living inside the C++ shared arena."""

    def __init__(self, view: memoryview, name: str, size: int):
        self.view = view
        self.name = name
        self.size = size

    @property
    def buf(self) -> memoryview:  # writer-side API parity with ShmSegment
        return self.view

    def close(self):
        try:
            self.view.release()
        except Exception:
            pass


@dataclass
class _Entry:
    name: str           # shm segment name, or "@<arena_path>:<offset>"
    size: int
    sealed: bool = False
    spilled_path: Optional[str] = None
    pinned: int = 0     # pin count (in-use by local get buffers)
    arena_offset: Optional[int] = None
    created_at: float = field(default_factory=time.monotonic)


class SharedObjectStore:
    """Node-local store daemon state: segment registry + eviction + spill.

    Thread-safe; lives inside the raylet process. Producer workers create and
    write segments directly (zero-copy path) and then `seal()` them here;
    consumer workers `get()` the segment name and attach read-only.
    """

    def __init__(self, capacity: Optional[int] = None, spill_dir: Optional[str] = None):
        cfg = get_config()
        self.capacity = capacity or cfg.object_store_memory
        self.spill_dir = spill_dir or os.path.join(cfg.session_dir_root, "spill", str(os.getpid()))
        self._entries: "OrderedDict[ObjectID, _Entry]" = OrderedDict()  # LRU order
        self._lock = threading.RLock()
        self._used = 0
        # unique per store instance: several raylets (and their stores) can
        # share one process in in-process test clusters
        self._prefix = f"rtpu-{os.getpid()}-{os.urandom(3).hex()}-"
        self._seq = 0
        # C++ arena for small objects: one mmap, sub-allocated (plasma's
        # dlmalloc-arena design); file-per-object remains the big-object path
        self.arena_threshold = 1 << 20  # 1 MiB
        self._arena = None
        try:
            from ray_tpu.core.arena import Arena

            arena_cap = max(64 << 20, min(self.capacity // 4, 512 << 20))
            self._arena = Arena.create(
                os.path.join(_SHM_DIR, f"{self._prefix}arena"), arena_cap)
        except Exception:
            logger.debug("arena unavailable", exc_info=True)

    # ---- producer API ----------------------------------------------------
    def create(self, object_id: ObjectID, size: int) -> ShmSegment:
        """Allocate a segment for `object_id`; caller writes then seals."""
        with self._lock:
            if object_id in self._entries:
                raise FileExistsError(f"object {object_id} already exists")
            self._maybe_evict(size)
            if self._arena is not None and size <= self.arena_threshold:
                off = self._arena.alloc(size)
                if off is not None:
                    name = f"@{self._arena.path}:{off}"
                    self._entries[object_id] = _Entry(
                        name=name, size=size, arena_offset=off)
                    self._used += size
                    return ArenaBuffer(self._arena.view(off, size), name, size)
            shm = None
            for _ in range(1000):
                self._seq += 1
                name = f"{self._prefix}{self._seq}"
                try:
                    shm = ShmSegment(name, size, create=True)
                    break
                except FileExistsError:
                    continue  # stale segment from a crashed prior run
            if shm is None:
                raise RuntimeError("could not allocate shm segment")
            self._entries[object_id] = _Entry(name=name, size=size)
            self._used += size
            return shm

    def seal(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                raise KeyError(f"object {object_id} not found")
            e.sealed = True
            self._entries.move_to_end(object_id)

    def put_bytes(self, object_id: ObjectID, data: bytes | memoryview) -> None:
        shm = self.create(object_id, len(data))
        try:
            shm.buf[: len(data)] = data
        finally:
            shm.close()
        self.seal(object_id)

    def adopt_local_copy(self, object_id: ObjectID, src_name: str,
                         size: int) -> bool:
        """Same-host 'transfer' fast path: both raylets share this host's
        /dev/shm, so materializing the object is a KERNEL-side file copy
        (copy_file_range, parallelized across ranges on multi-core hosts) —
        no sockets, no serialization, and no mmap fault-zeroing pass (file
        writes populate fresh tmpfs pages directly). This is the moral
        equivalent of the reference's same-node plasma sharing: one store
        per node means local consumers never stream bytes at all.

        Returns False (leaving no entry behind) if the source segment is
        not visible locally or vanished mid-copy; raises FileExistsError
        like create() if the object is already materializing here."""
        if src_name.startswith("@"):
            return False  # arena-resident (small) objects: not a shm file
        src_path = os.path.join(_SHM_DIR, src_name)
        try:
            if os.path.getsize(src_path) < size:
                return False
        except OSError:
            return False
        dst = self.create(object_id, size)  # may raise FileExistsError
        ok = False
        try:
            if not hasattr(dst, "name") or dst.name.startswith("@"):
                # landed in the arena: copy through the mapping
                with open(src_path, "rb") as f:
                    dst.buf[:size] = f.read(size)
                ok = True
                return True
            dst_path = os.path.join(_SHM_DIR, dst.name)
            sfd = os.open(src_path, os.O_RDONLY)
            try:
                dfd = os.open(dst_path, os.O_RDWR)
                try:
                    n_par = min(os.cpu_count() or 1, 4,
                                max(1, size // (64 << 20)))
                    ok = self._copy_ranges(sfd, dfd, size, n_par)
                finally:
                    os.close(dfd)
            finally:
                os.close(sfd)
            return ok
        finally:
            dst.close()
            if ok:
                self.seal(object_id)
            else:
                self.delete(object_id)

    @staticmethod
    def _copy_ranges(sfd: int, dfd: int, size: int, n_par: int) -> bool:
        def copy_range(off: int, end: int) -> None:
            while off < end:
                r = os.copy_file_range(sfd, dfd, end - off, off, off)
                if r == 0:
                    raise OSError("source segment truncated mid-copy")
                off += r

        from ray_tpu.core.data_plane import fan_out

        step = -(-size // max(1, n_par))
        errors = fan_out([lambda o=o: copy_range(o, min(o + step, size))
                          for o in range(0, size, step)])
        return not errors

    # ---- consumer API ----------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed

    def lookup(self, object_id: ObjectID) -> Optional[tuple[str, int]]:
        """Return (segment_name, size) for a sealed object, restoring from
        spill if needed; None if absent."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed:
                return None
            if e.spilled_path is not None:
                self._restore(object_id, e)
            self._entries.move_to_end(object_id)
            return (e.name, e.size)

    def get_buffer(self, object_id: ObjectID):
        """In-process zero-copy read (same process as the store)."""
        loc = self.lookup(object_id)
        if loc is None:
            return None
        name, size = loc
        return attach_object(name, size)

    def read_bytes(self, object_id: ObjectID) -> Optional[bytes]:
        buf = self.get_buffer(object_id)
        if buf is None:
            return None
        try:
            return bytes(buf.view)
        finally:
            buf.close()

    # ---- lifecycle -------------------------------------------------------
    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.pop(object_id, None)
            if e is None:
                return
            if e.arena_offset is not None:
                if self._arena is not None:
                    self._arena.free(e.arena_offset)
                self._used -= e.size
            elif e.spilled_path is None:
                self._unlink(e)
                self._used -= e.size
            elif os.path.exists(e.spilled_path):
                try:
                    os.unlink(e.spilled_path)
                except OSError:
                    pass

    def stats(self) -> dict:
        with self._lock:
            spilled = sum(1 for e in self._entries.values() if e.spilled_path)
            return {
                "num_objects": len(self._entries),
                "used_bytes": self._used,
                "capacity_bytes": self.capacity,
                "num_spilled": spilled,
            }

    def shutdown(self) -> None:
        with self._lock:
            for oid in list(self._entries):
                self.delete(oid)
            if self._arena is not None:
                self._arena.close()
                self._arena.unlink()
                self._arena = None

    # ---- internals -------------------------------------------------------
    def _unlink(self, e: _Entry) -> None:
        ShmSegment.unlink(e.name)

    def _maybe_evict(self, incoming: int) -> None:
        """Spill least-recently-used sealed objects until there is room.

        Mirrors the reference's threshold-triggered spilling
        (`object_spilling_threshold` 0.8, `ray_config_def.h:583`).
        """
        threshold = get_config().object_spilling_threshold
        if self._used + incoming <= self.capacity * threshold:
            return
        for oid in list(self._entries):
            if self._used + incoming <= self.capacity * threshold:
                break
            e = self._entries[oid]
            if (not e.sealed or e.spilled_path is not None or e.pinned > 0
                    or e.arena_offset is not None):
                continue  # arena objects are small; only file segments spill
            self._spill(oid, e)

    def _spill(self, object_id: ObjectID, e: _Entry) -> None:
        os.makedirs(self.spill_dir, exist_ok=True)
        path = os.path.join(self.spill_dir, object_id.hex())
        try:
            shm = ShmSegment(e.name, e.size)
            with open(path, "wb") as f:
                f.write(shm.buf[: e.size])
            shm.close()
        except FileNotFoundError:
            return
        self._unlink(e)
        e.spilled_path = path
        self._used -= e.size
        logger.debug("spilled %s (%d bytes) to %s", object_id, e.size, path)

    def _restore(self, object_id: ObjectID, e: _Entry) -> None:
        assert e.spilled_path is not None
        self._maybe_evict(e.size)
        self._seq += 1
        name = f"{self._prefix}r{self._seq}"
        shm = ShmSegment(name, e.size, create=True)
        shm.buf[: e.size] = open(e.spilled_path, "rb").read()
        shm.close()
        try:
            os.unlink(e.spilled_path)
        except OSError:
            pass
        e.name = name
        e.spilled_path = None
        self._used += e.size
        logger.debug("restored %s from spill", object_id)


def attach_object(name: str, size: int):
    """Attach to a sealed object from any process on the node.

    `name` is either a /dev/shm segment name or "@<arena_path>:<offset>"
    for objects living in the C++ shared arena.
    """
    if name.startswith("@"):
        from ray_tpu.core.arena import attached_arena

        path, off = name[1:].rsplit(":", 1)
        arena = attached_arena(path)
        if arena is None:
            raise FileNotFoundError(f"cannot attach arena {path}")
        return ArenaBuffer(arena.view(int(off), size), name, size)
    return SharedBuffer(ShmSegment(name, size), size)
