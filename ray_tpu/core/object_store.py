"""Per-node shared-memory object store + per-process in-memory store.

Equivalent role to the reference's plasma store
(`src/ray/object_manager/plasma/store.h:55`): immutable objects in shared
memory, one store per node, zero-copy reads from any worker process on that
node, LRU eviction and disk spilling when over budget
(cf. `ray_config_def.h:557-599`).

Redesign rationale (deliberate, documented per SURVEY §2.1): instead of one
mmap'd dlmalloc arena with fd passing over a unix socket (`plasma/fling.cc`),
each object is a named POSIX shared-memory segment (a /dev/shm tmpfs file,
see `ShmSegment`), created by whichever process produces the object and
attached by name from any process on the node. The kernel plays
the role of the arena allocator; eviction/spilling policy stays in the store
daemon. This removes an entire custom allocator + fd-passing protocol while
keeping the zero-copy property that matters on TPU hosts: a worker maps the
segment and hands `jax.device_put` a numpy view with no host-side copy.

Two tiers, matching reference semantics (SURVEY appendix C):
  - objects <= max_direct_call_object_size (100 KiB) travel inline in RPC
    replies into the owner's in-process object table (worker.py) — no shm
    round-trip;
  - larger objects land in the node `SharedObjectStore`, and only their
    location travels on the wire.
"""

from __future__ import annotations

import logging
import mmap
import os
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.serialization import SerializedObject

logger = logging.getLogger(__name__)

_SHM_DIR = "/dev/shm"


class ShmSegment:
    """A named shared-memory segment backed by a /dev/shm file.

    We deliberately bypass `multiprocessing.shared_memory`: its per-process
    resource tracker assumes single-process ownership and unlinks (or
    complains about) segments owned by the store daemon. A plain tmpfs file
    + mmap gives identical performance with explicit lifetime control —
    the store daemon alone unlinks.
    """

    def __init__(self, name: str, size: int, create: bool = False,
                 readonly: bool = False, file_size: Optional[int] = None):
        self.name = name
        path = os.path.join(_SHM_DIR, name)
        if readonly:
            fd = os.open(path, os.O_RDONLY)
        else:
            flags = os.O_RDWR | (os.O_CREAT | os.O_EXCL if create else 0)
            fd = os.open(path, flags, 0o600)
        try:
            if create:
                # file_size may exceed the mapped size: the store sizes
                # files to page-rounded buckets so the reuse pool can hand
                # a segment to any object in the same bucket
                os.ftruncate(fd, max(file_size or size, 1))
            if readonly:
                # PROT_READ mapping: every view (and every numpy array
                # reconstructed over one) is read-only — the aliasing
                # contract for zero-copy get()
                self._mmap = mmap.mmap(fd, max(size, 1),
                                       prot=mmap.PROT_READ)
            else:
                self._mmap = mmap.mmap(fd, max(size, 1))
        finally:
            os.close(fd)

    @property
    def buf(self) -> memoryview:
        return memoryview(self._mmap)

    def close(self) -> None:
        try:
            self._mmap.close()
        except (BufferError, ValueError):
            pass  # exported views still alive; kernel reclaims at unmap

    @staticmethod
    def unlink(name: str) -> None:
        try:
            os.unlink(os.path.join(_SHM_DIR, name))
        except FileNotFoundError:
            pass


class SharedBuffer:
    """A zero-copy view of an object living in a shared-memory segment."""

    def __init__(self, shm: ShmSegment, size: int):
        self._shm = shm
        self.view = shm.buf[:size]
        self.name = shm.name
        self.size = size

    def close(self):
        try:
            self.view.release()
        except Exception:
            pass
        self._shm.close()


class ArenaBuffer:
    """A zero-copy view of an object living inside the C++ shared arena."""

    def __init__(self, view: memoryview, name: str, size: int):
        self.view = view
        self.name = name
        self.size = size

    @property
    def buf(self) -> memoryview:  # writer-side API parity with ShmSegment
        return self.view

    def close(self):
        try:
            self.view.release()
        except Exception:
            pass


@dataclass
class _Entry:
    name: str           # shm segment name, or "@<arena_path>:<offset>"
    size: int
    sealed: bool = False
    spilled_path: Optional[str] = None
    pinned: int = 0     # pin count (live zero-copy reader views)
    doomed: bool = False  # deleted while pinned: unlink deferred to last unpin
    arena_offset: Optional[int] = None
    created_at: float = field(default_factory=time.monotonic)


class SharedObjectStore:
    """Node-local store daemon state: segment registry + eviction + spill.

    Thread-safe; lives inside the raylet process. Producer workers create and
    write segments directly (zero-copy path) and then `seal()` them here;
    consumer workers `get()` the segment name and attach read-only.
    """

    def __init__(self, capacity: Optional[int] = None, spill_dir: Optional[str] = None):
        cfg = get_config()
        self.capacity = capacity or cfg.object_store_memory
        self.spill_dir = spill_dir or os.path.join(cfg.session_dir_root, "spill", str(os.getpid()))
        self._entries: "OrderedDict[ObjectID, _Entry]" = OrderedDict()  # LRU order
        self._lock = threading.RLock()
        self._used = 0
        # Segment-reuse pool: deleted (unpinned, unspilled) file segments
        # park here instead of unlinking, bucketed by their page-rounded
        # file size. Reusing a segment hands the writer ALREADY-FAULTED
        # tmpfs pages — a large put costs one memcpy into hot pages
        # (~4-5x the fresh-page path, which pays allocation + zeroing).
        # Safe against stale readers because consumers confirm a pin of
        # the ObjectID (and the segment name it maps to) before trusting
        # an attached view; a recycled inode fails that confirmation.
        self._pool: Dict[int, list] = {}   # file_size -> [names]
        self._pool_bytes = 0
        # never let idle pooled segments crowd out live objects: the pool
        # is capped at a quarter of the store even when the knob is larger
        self._pool_cap = min(cfg.object_segment_pool_bytes,
                             self.capacity // 4)
        # unique per store instance: several raylets (and their stores) can
        # share one process in in-process test clusters
        self._prefix = f"rtpu-{os.getpid()}-{os.urandom(3).hex()}-"
        self._seq = 0
        # C++ arena for small objects: one mmap, sub-allocated (plasma's
        # dlmalloc-arena design); file-per-object remains the big-object path
        self.arena_threshold = 1 << 20  # 1 MiB
        self._arena = None
        try:
            from ray_tpu.core.arena import Arena

            arena_cap = max(64 << 20, min(self.capacity // 4, 512 << 20))
            self._arena = Arena.create(
                os.path.join(_SHM_DIR, f"{self._prefix}arena"), arena_cap)
        except Exception:
            logger.debug("arena unavailable", exc_info=True)

    # ---- producer API ----------------------------------------------------
    @staticmethod
    def _bucket(size: int) -> int:
        return (max(size, 1) + 4095) & ~4095  # page-rounded file size

    def create(self, object_id: ObjectID, size: int,
               info: Optional[dict] = None) -> ShmSegment:
        """Allocate a segment for `object_id`; caller writes then seals.
        `info`, when given, is filled with {"recycled": bool} so the writer
        can pick its write strategy (mmap memcpy into hot recycled pages vs
        writev into a fresh file)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None:
                if e.doomed and e.sealed:
                    # re-put of an object deleted while readers were still
                    # pinned (lineage re-execution): the immutable old copy
                    # IS the object — resurrect it instead of reallocating
                    e.doomed = False
                raise FileExistsError(f"object {object_id} already exists")
            self._maybe_evict(size)
            if self._arena is not None and size <= self.arena_threshold:
                off = self._arena.alloc(size)
                if off is not None:
                    name = f"@{self._arena.path}:{off}"
                    self._entries[object_id] = _Entry(
                        name=name, size=size, arena_offset=off)
                    self._used += size
                    return ArenaBuffer(self._arena.view(off, size), name, size)
            shm, recycled = self._alloc_file_segment(size)
            if info is not None:
                info["recycled"] = recycled
            self._entries[object_id] = _Entry(name=shm.name, size=size)
            self._used += size
            return shm

    def _alloc_file_segment(self, size: int):
        """Caller holds _lock. Returns (ShmSegment, recycled)."""
        bucket = self._bucket(size)
        names = self._pool.get(bucket)
        while names:
            name = names.pop()
            self._pool_bytes -= bucket
            try:
                return ShmSegment(name, size), True
            except OSError:
                continue  # swept by an external cleaner; fall through
        shm = None
        for _ in range(1000):
            self._seq += 1
            name = f"{self._prefix}{self._seq}"
            try:
                shm = ShmSegment(name, size, create=True, file_size=bucket)
                break
            except FileExistsError:
                continue  # stale segment from a crashed prior run
        if shm is None:
            raise RuntimeError("could not allocate shm segment")
        return shm, False

    def seal(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                raise KeyError(f"object {object_id} not found")
            e.sealed = True
            self._entries.move_to_end(object_id)

    def put_bytes(self, object_id: ObjectID, data: bytes | memoryview) -> None:
        n = len(data) if isinstance(data, (bytes, bytearray)) else data.nbytes
        shm = self.create(object_id, n)
        try:
            if shm.name.startswith("@"):
                shm.buf[:n] = data
            else:
                # fd write, not the mapping: populates tmpfs pages directly
                # instead of zero-faulting a fresh mapping first (and on a
                # recycled segment skips repopulating the page table)
                fd = os.open(os.path.join(_SHM_DIR, shm.name), os.O_WRONLY)
                try:
                    mv = memoryview(data)
                    if mv.format != "B" or mv.ndim != 1:
                        mv = mv.cast("B")
                    off = 0
                    while off < n:
                        off += os.write(fd, mv[off:])
                finally:
                    os.close(fd)
        finally:
            shm.close()
        self.seal(object_id)

    def adopt_local_copy(self, object_id: ObjectID, src_name: str,
                         size: int) -> bool:
        """Same-host 'transfer' fast path: both raylets share this host's
        /dev/shm, so materializing the object is a KERNEL-side file copy
        (copy_file_range, parallelized across ranges on multi-core hosts) —
        no sockets, no serialization, and no mmap fault-zeroing pass (file
        writes populate fresh tmpfs pages directly). This is the moral
        equivalent of the reference's same-node plasma sharing: one store
        per node means local consumers never stream bytes at all.

        Returns False (leaving no entry behind) if the source segment is
        not visible locally or vanished mid-copy; raises FileExistsError
        like create() if the object is already materializing here."""
        if src_name.startswith("@"):
            return False  # arena-resident (small) objects: not a shm file
        src_path = os.path.join(_SHM_DIR, src_name)
        try:
            if os.path.getsize(src_path) < size:
                return False
        except OSError:
            return False
        dst = self.create(object_id, size)  # may raise FileExistsError
        ok = False
        try:
            if not hasattr(dst, "name") or dst.name.startswith("@"):
                # landed in the arena: copy through the mapping
                with open(src_path, "rb") as f:
                    dst.buf[:size] = f.read(size)
                ok = True
                return True
            dst_path = os.path.join(_SHM_DIR, dst.name)
            sfd = os.open(src_path, os.O_RDONLY)
            try:
                dfd = os.open(dst_path, os.O_RDWR)
                try:
                    n_par = min(os.cpu_count() or 1, 4,
                                max(1, size // (64 << 20)))
                    ok = self._copy_ranges(sfd, dfd, size, n_par)
                finally:
                    os.close(dfd)
            finally:
                os.close(sfd)
            return ok
        finally:
            dst.close()
            if ok:
                self.seal(object_id)
            else:
                self.delete(object_id)

    @staticmethod
    def _copy_ranges(sfd: int, dfd: int, size: int, n_par: int) -> bool:
        def copy_range(off: int, end: int) -> None:
            while off < end:
                r = os.copy_file_range(sfd, dfd, end - off, off, off)
                if r == 0:
                    raise OSError("source segment truncated mid-copy")
                off += r

        from ray_tpu.core.data_plane import fan_out

        step = -(-size // max(1, n_par))
        errors = fan_out([lambda o=o: copy_range(o, min(o + step, size))
                          for o in range(0, size, step)])
        return not errors

    # ---- consumer API ----------------------------------------------------
    def status(self, object_id: ObjectID) -> Optional[str]:
        """"sealed" | "unsealed" | None (absent or deleted-while-pinned)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or e.doomed:
                return None
            return "sealed" if e.sealed else "unsealed"

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(object_id)
            return e is not None and e.sealed and not e.doomed

    def lookup(self, object_id: ObjectID) -> Optional[tuple[str, int]]:
        """Return (segment_name, size) for a sealed object, restoring from
        spill if needed; None if absent (or deleted-but-pinned)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed or e.doomed:
                return None
            if e.spilled_path is not None:
                self._restore(object_id, e)
            self._entries.move_to_end(object_id)
            return (e.name, e.size)

    # ---- pin protocol ----------------------------------------------------
    def pin(self, object_id: ObjectID) -> Optional[tuple[str, int]]:
        """Pin a sealed object for a zero-copy reader and return its
        CURRENT (segment_name, size); None if absent/unsealed/doomed.
        While pinned the entry is excluded from spill and eviction, and a
        delete() defers the unlink until the last unpin — so reader views
        into the segment stay valid (and accounted) for their lifetime.
        Restores from spill first: pinning declares intent to attach."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None or not e.sealed or e.doomed:
                return None
            if e.spilled_path is not None:
                self._restore(object_id, e)
            e.pinned += 1
            self._entries.move_to_end(object_id)
            return (e.name, e.size)

    def unpin(self, object_id: ObjectID) -> None:
        """Release one pin; finishes a deferred delete at the last one.
        Unknown ids are ignored (a reader's compensating unpin after a
        failed attach may race the owner's delete)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return
            e.pinned = max(0, e.pinned - 1)
            if e.doomed and e.pinned == 0:
                self._entries.pop(object_id, None)
                if e.arena_offset is not None:
                    if self._arena is not None:
                        self._arena.free(e.arena_offset)
                    self._used -= e.size
                else:
                    self._reclaim(e)

    def get_buffer(self, object_id: ObjectID):
        """In-process zero-copy read (same process as the store). The
        buffer holds a PIN until close() — under the segment-reuse pool an
        unpinned attach would be unsafe (a concurrent delete could recycle
        and overwrite the inode beneath the view), so callers MUST close.
        Scoped readers should prefer pinned_view."""
        loc = self.pin(object_id)
        if loc is None:
            return None
        try:
            buf = attach_object(*loc)
        except (FileNotFoundError, OSError):
            self.unpin(object_id)
            return None
        inner_close = buf.close
        released = []

        def close():
            if not released:
                released.append(True)
                inner_close()
                self.unpin(object_id)

        buf.close = close
        return buf

    @contextmanager
    def pinned_view(self, object_id: ObjectID):
        """Pin + attach + release in one scope: the shared from-view read
        used by every server-side consumer (data-plane fetch, RPC chunk
        serves). The pin keeps the segment out of spill/eviction for the
        duration, so a long transfer can't race a spill into a
        double-IO restore (or a recycled inode). Yields the buffer, or
        None when the object is absent."""
        loc = self.pin(object_id)
        if loc is None:
            yield None
            return
        buf = None
        try:
            try:
                buf = attach_object(*loc, readonly=True)
            except (FileNotFoundError, OSError):
                yield None
                return
            yield buf
        finally:
            if buf is not None:
                buf.close()
            self.unpin(object_id)

    def read_bytes(self, object_id: ObjectID) -> Optional[bytes]:
        """Materializing read — ONLY for callers that need owned bytes
        (the wire). Consumers that immediately deserialize should use
        pinned_view + serialization.loads instead (no intermediate copy)."""
        with self.pinned_view(object_id) as buf:
            if buf is None:
                return None
            return bytes(buf.view)

    # ---- lifecycle -------------------------------------------------------
    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e is None:
                return
            if e.pinned > 0 and e.spilled_path is None:
                # zero-copy (or pinned_view) readers still hold views into
                # the segment / arena slot: hide the entry (lookup/contains
                # say gone) but defer the reclaim — the last unpin runs it
                e.doomed = True
                return
            self._entries.pop(object_id, None)
            if e.arena_offset is not None:
                if self._arena is not None:
                    self._arena.free(e.arena_offset)
                self._used -= e.size
            elif e.spilled_path is None:
                self._reclaim(e)
            elif os.path.exists(e.spilled_path):
                try:
                    os.unlink(e.spilled_path)
                except OSError:
                    pass

    def _reclaim(self, e: _Entry) -> None:
        """Caller holds _lock. Retire a live file segment: park it in the
        reuse pool (pages stay hot for the next same-bucket create),
        evicting older pooled segments to make room — the workload's
        CURRENT object size wins the pool. Oversized segments unlink."""
        self._used -= e.size
        bucket = self._bucket(e.size)
        if bucket > self._pool_cap:
            self._unlink(e)
            return
        need = self._pool_bytes + bucket - self._pool_cap
        if need > 0:
            self._drain_pool(need)
        self._pool.setdefault(bucket, []).append(e.name)
        self._pool_bytes += bucket

    def _drain_pool(self, want: int) -> int:
        """Caller holds _lock. Unlink pooled segments until `want` bytes
        are freed (memory pressure beats reuse warmth). Returns freed."""
        freed = 0
        for bucket in sorted(self._pool, reverse=True):
            names = self._pool[bucket]
            while names and freed < want:
                ShmSegment.unlink(names.pop())
                self._pool_bytes -= bucket
                freed += bucket
            if freed >= want:
                break
        return freed

    def stats(self) -> dict:
        with self._lock:
            spilled = sum(1 for e in self._entries.values() if e.spilled_path)
            pinned = sum(1 for e in self._entries.values() if e.pinned > 0)
            return {
                "num_objects": len(self._entries),
                "used_bytes": self._used,
                "capacity_bytes": self.capacity,
                "num_spilled": spilled,
                "num_pinned": pinned,
                "pinned_refs": sum(e.pinned for e in self._entries.values()),
                "pool_bytes": self._pool_bytes,
            }

    def shutdown(self) -> None:
        with self._lock:
            for oid, e in list(self._entries.items()):
                e.pinned = 0  # process exiting: force-reclaim
                e.doomed = False
                self.delete(oid)
            self._drain_pool(self._pool_bytes)
            if self._arena is not None:
                self._arena.close()
                self._arena.unlink()
                self._arena = None

    # ---- internals -------------------------------------------------------
    def _unlink(self, e: _Entry) -> None:
        ShmSegment.unlink(e.name)

    def _maybe_evict(self, incoming: int) -> None:
        """Spill least-recently-used sealed objects until there is room.

        Mirrors the reference's threshold-triggered spilling
        (`object_spilling_threshold` 0.8, `ray_config_def.h:583`).
        """
        threshold = get_config().object_spilling_threshold
        budget = self.capacity * threshold - self._pool_bytes
        if self._used + incoming <= budget:
            return
        # reclaim idle pooled segments before spilling LIVE objects: pool
        # warmth never costs a spill
        self._drain_pool(int(self._used + incoming - budget))
        budget = self.capacity * threshold - self._pool_bytes
        for oid in list(self._entries):
            if self._used + incoming <= budget:
                break
            e = self._entries[oid]
            if (not e.sealed or e.spilled_path is not None or e.pinned > 0
                    or e.arena_offset is not None):
                continue  # pinned entries hold reader views; arena objects
                # are small — only idle file segments spill
            self._spill(oid, e)

    def _spill(self, object_id: ObjectID, e: _Entry) -> None:
        os.makedirs(self.spill_dir, exist_ok=True)
        path = os.path.join(self.spill_dir, object_id.hex())
        try:
            shm = ShmSegment(e.name, e.size)
            with open(path, "wb") as f:
                f.write(shm.buf[: e.size])
            shm.close()
        except FileNotFoundError:
            return
        self._unlink(e)
        e.spilled_path = path
        self._used -= e.size
        logger.debug("spilled %s (%d bytes) to %s", object_id, e.size, path)

    def _restore(self, object_id: ObjectID, e: _Entry) -> None:
        assert e.spilled_path is not None
        self._maybe_evict(e.size)
        shm, _ = self._alloc_file_segment(e.size)
        name = shm.name
        with open(e.spilled_path, "rb") as f:
            shm.buf[: e.size] = f.read(e.size)
        shm.close()
        try:
            os.unlink(e.spilled_path)
        except OSError:
            pass
        e.name = name
        e.spilled_path = None
        self._used += e.size
        logger.debug("restored %s from spill", object_id)


def attach_object(name: str, size: int, readonly: bool = False):
    """Attach to a sealed object from any process on the node.

    `name` is either a /dev/shm segment name or "@<arena_path>:<offset>"
    for objects living in the C++ shared arena. With `readonly` the
    mapping is PROT_READ, so every view (and numpy array over one) is
    immutable — the aliasing contract for zero-copy get().
    """
    if name.startswith("@"):
        from ray_tpu.core.arena import attached_arena

        path, off = name[1:].rsplit(":", 1)
        arena = attached_arena(path)
        if arena is None:
            raise FileNotFoundError(f"cannot attach arena {path}")
        return ArenaBuffer(arena.view(int(off), size), name, size)
    return SharedBuffer(ShmSegment(name, size, readonly=readonly), size)
