"""Pluggable control-plane snapshot storage.

The role Redis plays for the reference's HA GCS (`gcs_table_storage.h`,
`redis_client.h`): the GCS serializes its durable tables into an opaque
blob and hands it to a `SnapshotStore` — a dumb keyed blob interface
(`put`/`get`/`list_keys`/`delete`) selected by URI, so the storage
backend is swappable without touching the control plane:

    file:///var/lib/ray_tpu/gcs     -> FileSnapshotStore (atomic rename)
    memory://name                   -> MemorySnapshotStore (per-process,
                                       survives a GcsServer object swap —
                                       the in-process test analog of an
                                       external store)

Blobs are written through a checksummed envelope (`encode_blob` /
`decode_blob`: magic + sha256 + payload) and `VersionedSnapshots` layers
monotonically-numbered keys on top, so a restore walks versions newest
first and a torn/corrupt write falls back to the previous good snapshot
instead of silently restoring garbage.
"""

from __future__ import annotations

import hashlib
import logging
import os
import struct
import threading
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

# envelope: MAGIC + u32 format version + sha256(payload) + payload
_MAGIC = b"RTPUSNAP"
_FORMAT_VERSION = 1
_HDR = struct.Struct("!8sI32s")


class SnapshotCorruptError(ValueError):
    """Blob failed the envelope checks (magic/version/checksum)."""


def encode_blob(payload: bytes) -> bytes:
    digest = hashlib.sha256(payload).digest()
    return _HDR.pack(_MAGIC, _FORMAT_VERSION, digest) + payload


def decode_blob(blob: bytes) -> bytes:
    if len(blob) < _HDR.size:
        raise SnapshotCorruptError("snapshot blob truncated")
    magic, version, digest = _HDR.unpack_from(blob, 0)
    if magic != _MAGIC:
        raise SnapshotCorruptError("bad snapshot magic")
    if version != _FORMAT_VERSION:
        raise SnapshotCorruptError(f"unsupported snapshot format {version}")
    payload = blob[_HDR.size:]
    if hashlib.sha256(payload).digest() != digest:
        raise SnapshotCorruptError("snapshot checksum mismatch")
    return payload


class SnapshotStore:
    """Keyed blob storage. Implementations must make `put` atomic per key
    (a reader never observes a half-written blob)."""

    def put(self, key: str, blob: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def list_keys(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError


class FileSnapshotStore(SnapshotStore):
    """Directory of blob files; atomic via tmp-write + os.replace — the
    same swap discipline the old single-pickle path used, now per key."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        if "/" in key or key.startswith("."):
            raise ValueError(f"invalid snapshot key {key!r}")
        return os.path.join(self.root, key)

    def put(self, key: str, blob: bytes) -> None:
        path = self._path(key)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def list_keys(self, prefix: str = "") -> List[str]:
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return []
        return sorted(n for n in names
                      if n.startswith(prefix) and ".tmp" not in n)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass


class MemorySnapshotStore(SnapshotStore):
    """Process-global named keyspaces: a replacement GcsServer object in
    the same process (tests, embedded heads) restores from the old one's
    writes — the in-process stand-in for an external blob service."""

    _spaces: Dict[str, Dict[str, bytes]] = {}
    _spaces_lock = threading.Lock()

    def __init__(self, name: str):
        self.name = name
        with MemorySnapshotStore._spaces_lock:
            self._blobs = MemorySnapshotStore._spaces.setdefault(name, {})
        self._lock = threading.Lock()

    def put(self, key: str, blob: bytes) -> None:
        with self._lock:
            self._blobs[key] = bytes(blob)

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._blobs.get(key)

    def list_keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(k for k in self._blobs if k.startswith(prefix))

    def delete(self, key: str) -> None:
        with self._lock:
            self._blobs.pop(key, None)

    @classmethod
    def wipe(cls, name: str) -> None:
        """Test helper: drop a named keyspace."""
        with cls._spaces_lock:
            cls._spaces.pop(name, None)


def store_from_uri(uri: str) -> SnapshotStore:
    """`file://<dir>` or `memory://<name>`; a bare path means file."""
    if uri.startswith("file://"):
        return FileSnapshotStore(uri[len("file://"):])
    if uri.startswith("memory://"):
        return MemorySnapshotStore(uri[len("memory://"):])
    if "://" in uri:
        raise ValueError(f"unsupported snapshot store URI {uri!r} "
                         f"(supported: file://, memory://)")
    return FileSnapshotStore(uri)


class VersionedSnapshots:
    """Monotonically-versioned snapshots over a SnapshotStore.

    `save` writes `<prefix>-<seq>` (seq = newest seen + 1) through the
    checksummed envelope and prunes to the newest `keep` versions;
    `load_latest` walks versions newest-first and returns the first blob
    that decodes, logging and skipping corrupt ones.
    """

    def __init__(self, store: SnapshotStore, prefix: str = "gcs",
                 keep: int = 3):
        self.store = store
        self.prefix = prefix
        self.keep = max(1, keep)

    def _seq_of(self, key: str) -> Optional[int]:
        tail = key[len(self.prefix) + 1:]
        try:
            return int(tail)
        except ValueError:
            return None

    def _versions(self) -> List[int]:
        out = []
        for k in self.store.list_keys(prefix=f"{self.prefix}-"):
            seq = self._seq_of(k)
            if seq is not None:
                out.append(seq)
        return sorted(out)

    def save(self, payload: bytes) -> int:
        versions = self._versions()
        seq = (versions[-1] + 1) if versions else 1
        self.store.put(f"{self.prefix}-{seq:016d}", encode_blob(payload))
        for old in versions[:max(0, len(versions) + 1 - self.keep)]:
            self.store.delete(f"{self.prefix}-{old:016d}")
        return seq

    def load_latest(self) -> Optional[bytes]:
        payload, _ = self.load_latest_with_version()
        return payload

    def load_latest_with_version(self) -> tuple[Optional[bytes], int]:
        """Newest decodable payload AND its version number — the standby
        head's tail loop keys its freshness ("≤1 snapshot behind") on the
        version. (None, 0) when no usable snapshot exists."""
        for seq in reversed(self._versions()):
            key = f"{self.prefix}-{seq:016d}"
            blob = self.store.get(key)
            if blob is None:
                continue
            try:
                return decode_blob(blob), seq
            except SnapshotCorruptError as e:
                logger.warning("snapshot %s unusable (%s); trying the "
                               "previous version", key, e)
        return None, 0

    def latest_version(self) -> int:
        """Newest version number present (0 when empty) — a cheap list, no
        blob fetch; the standby polls this before pulling the payload."""
        versions = self._versions()
        return versions[-1] if versions else 0
