"""Env-overridable configuration registry.

Equivalent of the reference's `RAY_CONFIG(type, name, default)` macro table
(`src/ray/common/ray_config_def.h:1-814`, 199 knobs): every knob defined here
can be overridden on any process via the `RAY_TPU_<NAME>` environment
variable, so daemons spawned as subprocesses inherit overrides naturally.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, fields
from typing import Any


def _coerce(value: str, typ: type) -> Any:
    if typ is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    return value


@dataclass
class Config:
    # --- object store (cf. ray_config_def.h:213 max_direct_call_object_size) ---
    max_direct_call_object_size: int = 100 * 1024  # inline objects <= 100 KiB
    task_rpc_inlined_bytes_limit: int = 10 * 1024 * 1024
    object_store_memory: int = 2 * 1024 * 1024 * 1024  # per-node shm budget
    object_spilling_threshold: float = 0.8
    min_spilling_size: int = 100 * 1024 * 1024
    object_transfer_chunk_bytes: int = 8 * 1024 * 1024
    # --- zero-copy object plane (pin protocol; ROADMAP item 3) ---
    # same-node get() of a sealed plasma object attaches the shm segment
    # and deserializes IN PLACE: pickle-5 buffers stay read-only views
    # into the mapping, refcount-pinned on the raylet until the reader's
    # last view is GC'd (finalizer-driven obj_unpin; the raylet reaps a
    # dead reader's pins at connection close). Off -> every get copies.
    object_zero_copy_enabled: bool = True
    # worker-side LRU of (segment, size) locations: repeat gets of a hot
    # object skip owner resolution AND the pull round-trip entirely
    object_location_cache_entries: int = 4096
    # deleted file segments park here (bucketed by page-rounded size)
    # instead of unlinking: a recycled segment hands the next same-size
    # put ALREADY-FAULTED tmpfs pages (~4-5x the fresh-page write path).
    # Drained first under memory pressure; 0 disables recycling.
    object_segment_pool_bytes: int = 256 * 1024 * 1024
    # --- storage failure domain (checksummed spills, disk-full ladder,
    # store-full admission; cf. reference ObjectStoreFullError +
    # local_object_manager.h spill IO workers) ---
    # ":"-separated fallback spill directories. A spill write that fails
    # with ENOSPC/EIO retries down this list under backoff; empty = the
    # per-pid session spill dir only.
    object_spill_dirs: str = ""
    # per-directory write retries (with backoff) before the next dir
    spill_write_retries: int = 2
    spill_retry_backoff_ms: int = 50
    # once EVERY spill dir has failed the store goes spill-degraded: it
    # stops spilling (puts flip to backpressure) and probes the dirs at
    # this period until one heals. 0 disables the self-heal probe.
    spill_degraded_probe_period_s: float = 2.0
    # put()/obj_create block at most this long for eviction/unpin headroom
    # before failing with typed ObjectStoreFullError
    put_full_timeout_s: float = 10.0
    # reader pins may hold at most this fraction of capacity: the first
    # pin that would cross it is refused (readers fall back to a bounded
    # copy window), so pinned entries can never wedge eviction entirely
    max_pinned_fraction: float = 0.75

    # --- health / heartbeats (cf. gcs_health_check_manager.h) ---
    health_check_period_ms: int = 1000
    health_check_timeout_ms: int = 10000
    num_heartbeats_timeout: int = 5
    # gray-failure quarantine (partition failure domain): a node silent
    # past this bound — but not yet past the death bound — takes no NEW
    # dispatch and the autoscaler holds its replacement; it rejoins with
    # its actors intact if heartbeats resume before the death bound.
    # 0 = half of health_check_timeout_ms (always clamped inside it).
    node_quarantine_timeout_ms: int = 0

    # --- scheduling (cf. hybrid_scheduling_policy.cc, ray_config_def.h:193) ---
    scheduler_spread_threshold: float = 0.5
    worker_lease_timeout_ms: int = 30000
    max_pending_lease_requests_per_scheduling_category: int = 10

    # --- worker pool (cf. worker_pool.h:156, PrestartWorkers
    # worker_pool.cc:1363) ---
    # FLOOR of the demand-driven prestart policy (~1 worker/CPU up to the
    # current backlog): the default env keeps at least this many task
    # workers ALIVE (busy, idle or starting) from raylet boot onward, and
    # the idle reaper never shrinks the idle pool below this.
    num_prestart_workers: int = 0
    worker_register_timeout_s: int = 60
    idle_worker_killing_time_s: int = 300
    maximum_startup_concurrency: int = 8
    # --- warm worker pool (fork-template zygotes; core/worker_pool.py) ---
    # One template process per runtime-env key imports ray_tpu once and
    # os.fork()s a ready worker per granted lease; disable to force the
    # classic cold-Popen path everywhere.
    worker_template_enabled: bool = True
    worker_template_boot_timeout_s: float = 60.0
    worker_template_fork_timeout_s: float = 10.0
    # template crash -> respawn under full-jitter backoff (cold fallback
    # serves leases while the clock runs)
    worker_template_backoff_base_ms: int = 500
    worker_template_backoff_cap_ms: int = 30000
    # non-default-env templates close after this long with no fork and no
    # live worker (releasing their env ref so runtime-env gc can reclaim)
    worker_template_idle_s: float = 300.0

    # --- resource reporting / syncer ---
    resource_broadcast_period_ms: int = 100

    # --- core worker ---
    task_retry_delay_ms: int = 100
    max_task_retries_default: int = 0
    actor_max_restarts_default: int = 0
    get_check_interval_s: float = 0.05
    # Lineage-based object recovery (cf. reference
    # object_recovery_manager.h:41, task_manager.h:90): how many times a lost
    # task output may be recomputed by re-executing its creating task, and how
    # many creating specs the owner retains (FIFO-evicted beyond this).
    lineage_reconstruction_max_retries: int = 3
    lineage_table_max_entries: int = 10000
    # Grace before freeing a plasma object whose ref was serialized outward:
    # absorbs the window where a receiver's add_borrower notify is in flight
    # while the owner's last local ref dies (lineage recovery is the backstop
    # if the race is still lost).
    object_free_grace_period_ms: int = 500

    # --- memory monitor (cf. reference memory_monitor.h:52 +
    # worker_killing_policy.h:34: kill retriable tasks under node pressure) ---
    memory_monitor_refresh_ms: int = 250
    memory_usage_threshold: float = 0.95
    # 0 = monitor whole-node memory via psutil; >0 = budget for the summed
    # RSS of this raylet's task workers (deterministic for tests/containers)
    memory_monitor_worker_budget_bytes: int = 0
    # don't kill a task younger than this (it hasn't allocated yet), and
    # wait this long between kills (let the previous kill's memory return)
    memory_monitor_min_task_age_ms: int = 500
    memory_monitor_kill_cooldown_ms: int = 1000

    # --- data streaming executor (cf. reference streaming_executor.py:45:
    # operator-level backpressure; here: bounded in-flight block tasks
    # AND a per-operator byte budget on produced-but-unconsumed blocks,
    # the reference's per-op resource quota) ---
    data_max_inflight_blocks: int = 8
    data_op_memory_budget_bytes: int = 256 * 1024 * 1024

    # --- object transfer (cf. reference object_manager.h:117 64MiB chunks,
    # pull_manager.h:52 admission control, push_manager.h:29) ---
    object_transfer_chunk_size_bytes: int = 16 * 1024 * 1024
    object_transfer_inflight_chunks: int = 4
    object_transfer_chunk_timeout_s: float = 60.0
    # striped raw-socket pulls over the dedicated data plane (data_plane.py);
    # chunks interleave across this many persistent connections
    object_transfer_parallel_streams: int = 4
    # total bytes of concurrently-admitted chunked pulls per raylet; pulls
    # beyond it queue rather than overcommitting store memory
    pull_admission_max_bytes: int = 2 * 1024 * 1024 * 1024

    # --- task-path fast lanes ---
    # Export-once function table (cf. reference function_manager.py): the
    # submitter pickles a callable once, exports the blob to the GCS keyed
    # by its content hash, and every TaskSpec carries only the FunctionID.
    # Disabled -> every spec ships the full pickle (the fallback wire
    # format, kept for anonymous one-shot callables).
    function_table_enabled: bool = True
    # executor-side LRU of DESERIALIZED functions/classes per process
    function_cache_max_entries: int = 256
    # GCS-side table byte budget: beyond it the OLDEST exports evict (with
    # a warning — a task whose function was evicted fails its fetch). Keeps
    # a driver minting unbounded distinct closures from growing the GCS and
    # its snapshot forever.
    function_table_max_bytes: int = 1024 * 1024 * 1024
    # Worker-side TaskEventBuffer (cf. reference task_event_buffer.h,
    # task_events_report_interval_ms): task-state transitions and tracing
    # spans coalesce in-process and flush to the GCS on this timer (and at
    # shutdown) instead of one notify per transition.
    task_events_report_interval_ms: int = 200
    # bounded buffer: oldest events drop (counted) beyond this
    task_events_max_buffer_size: int = 10000

    # --- distributed tracing (util/tracing.py; cf. reference ProfileEvent
    # + opt-in OpenTelemetry context propagation) ---
    # Default-off master switch for trace-CONTEXT propagation: when on,
    # submits stamp (trace_id, parent span_id) into every TaskSpec, the
    # serve path and rollout->learner loop carry the same context, and the
    # raylet ships its lease spans. Local chrome-trace spans record either
    # way — the knob only gates the cross-process causal tree, so the
    # default keeps the task hot path free of any per-submit id minting.
    tracing_enabled: bool = False
    # in-process span ring bound (mirrors task_events_max_buffer_size):
    # oldest spans drop (counted; the count rides the next task-events
    # flush) so fork-template replicas / learner actors can't grow forever
    tracing_max_buffer_size: int = 20000
    # GCS-side trace ring: distinct trace_ids retained (oldest evicted)
    tracing_max_traces: int = 2000
    # NTP-style clock probe against the GCS (offset = t1 - (t0+t2)/2 from
    # one RPC round-trip): re-estimated at this period per process, shipped
    # with each task-events flush for merge-time alignment
    tracing_clock_probe_period_s: float = 30.0
    # storm flight recorder: seconds of span history dumped next to the
    # artifact when a harness violation fires
    tracing_flight_recorder_window_s: float = 30.0

    # --- completion-path fast lanes ---
    # Executor-side ResultBuffer (result_buffer.py): while a delivery is in
    # flight, further results batch per owner until this interval's edge;
    # with nothing in flight a result ships as soon as the flush thread
    # wakes, so a sequential caller's round-trips never wait out the
    # interval.
    result_buffer_flush_interval_ms: int = 10
    # per-result delivery attempts (one flush retry each) before results to
    # an unreachable owner are dropped with a warning
    result_delivery_max_attempts: int = 5

    # --- rpc ---
    rpc_connect_timeout_s: float = 30.0
    rpc_call_timeout_s: float = 0.0  # 0 = no timeout
    # Reconnect loops (control-plane links, owner links) sleep with
    # exponential backoff + full jitter between attempts (util/backoff.py)
    # instead of fixed sleeps: after a head replacement every process
    # reconnects at once, and jitter decorrelates the herd.
    reconnect_backoff_base_ms: int = 100
    reconnect_backoff_cap_ms: int = 10000

    # --- control-plane HA (cf. reference gcs_table_storage.h) ---
    # SnapshotStore URI for GCS persistence: "file:///path" or
    # "memory://name"; empty = no persistence. A replacement head started
    # on a NEW address restores node/actor/PG/KV state from this store.
    gcs_snapshot_uri: str = ""
    # retained snapshot versions (newest wins; corrupt falls back older)
    gcs_snapshot_keep: int = 3
    # Head re-resolution: a file holding the current GCS address. The GCS
    # writes it at boot; raylets/workers/drivers re-read it on every
    # reconnect attempt, so a replacement head on a new address is found
    # without any process restart. Empty = rely on the in-band announce
    # (the new head dials snapshot-known raylets) + static addresses.
    gcs_address_file: str = ""
    # a 2-phase PG bundle prepared but never committed (the head died
    # between phases) is returned to the node pool after this timeout
    bundle_prepare_timeout_s: float = 30.0
    # an actor whose restart found no capacity waits (paced retries) for a
    # surviving/replacement node at most this long before going DEAD —
    # unbounded waiting would hang every ref of a permanently-infeasible
    # restart (node type no longer launchable, breaker stuck open)
    actor_restart_pending_timeout_s: float = 120.0
    # --- standby head / lease fencing (core/head_lease.py) ---
    # TTL of the active head's lease (stored beside the snapshots); the
    # head renews every ttl/3, a standby promotes once it expires. Lower =
    # faster failover, more store writes.
    head_lease_ttl_s: float = 3.0
    # explicit renew period; 0 = ttl/3
    head_lease_renew_period_s: float = 0.0
    # standby snapshot-tail + lease-watch poll period; 0 = ttl/4
    head_standby_poll_s: float = 0.0
    # CH_RESOURCES fan-out ships per-node DELTAS between full snapshots
    # (full on topology change / subscriber catch-up) so gossip volume is
    # O(changes), not O(nodes) per publish x O(nodes) subscribers
    resource_broadcast_delta_enabled: bool = True

    # --- fault injection (deterministic chaos; see rpc.FaultInjector) ---
    # Rules at named client-side RPC boundaries, ";"-separated:
    #   drop:<method>[:<prob>]          lose the message
    #   delay:<method>:<ms>[:<prob>]    stall before send
    #   sever_once:<method>             cut the connection at first match
    #   sever:<method>[:<prob>]         cut the connection per match
    #   fs:<site>:<mode>[:<prob>]       filesystem fault at a named site
    #                                   (spill_write, spill_restore; modes
    #                                   enospc, eio, torn, bitflip)
    # <method> may be "*". Empty = injection disabled (zero overhead).
    fault_injection_spec: str = ""
    # seeds the injector's RNG so probabilistic faults replay exactly
    fault_injection_seed: int = 0

    # --- completion-path retry ---
    # cap for the owner-down result-redelivery backoff (base is the flush
    # interval; full jitter)
    result_retry_backoff_cap_ms: int = 2000

    # --- job failure domain (cancellation + driver-death fate-sharing) ---
    # RAY_TPU_JOB_REAP_DETECTION_BOUND_S: ceiling from driver death to the
    # GCS *initiating* the fleet reap. Conn-close detection is immediate;
    # this bounds the backstop paths (health-loop probe of a RUNNING job
    # whose driver link is gone, and post-failover probe of snapshot-
    # restored jobs whose conn-close hooks died with the old head).
    job_reap_detection_bound_s: float = 3.0
    # RAY_TPU_JOB_REAP_PACING_MS: sleep between per-target reap steps
    # (per-raylet purge notify, per-actor kill) so reaping a large job is a
    # paced drain, not a thundering herd against surviving tenants.
    job_reap_pacing_ms: int = 10
    # owner-side failsafe: after cancel() is sent, if no downstream ack
    # (dequeue notify, cooperative error, kill report) resolved the ref
    # within this window, the owner resolves it to TaskCancelledError
    # itself — a cancelled ref may never hang on a lost notify
    task_cancel_resolution_timeout_s: float = 10.0
    # force=True: cooperative interrupt is pushed first (lets a recursive
    # cancel fan out to children), SIGKILL follows after this grace
    task_cancel_force_grace_ms: int = 200

    # --- logging / session ---
    session_dir_root: str = "/tmp/ray_tpu"
    log_to_driver: bool = True

    # --- tpu topology ---
    tpu_chips_per_host: int = 4  # v5e default host shape
    tpu_slice_resource_name: str = "TPU"

    def __post_init__(self):
        for f in fields(self):
            env = os.environ.get(f"RAY_TPU_{f.name.upper()}")
            if env is not None:
                setattr(self, f.name, _coerce(env, type(getattr(self, f.name))))


_config: Config | None = None


def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config()
    return _config


def reset_config() -> None:
    global _config
    _config = None
