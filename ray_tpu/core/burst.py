"""Burst-elasticity chaos harness: scale a synthetic fleet 10 -> 1000
workers under queued load with seeded worker kills — and, in `--nodes`
mode, a multi-raylet NODE kill storm: whole nodes (raylet + its workers +
its fork templates) SIGKILLed together under closed-loop load, with the
autoscaler as the recovery control loop (dead-node reap-and-replace) and
warm node onboarding (hot-env template prewarm) measured end to end.

This is the elasticity story behind "millions of users" made into a
repeatable scenario: a small serving/RL-style fleet of actors is already
busy with a continuous stream of calls when demand arrives and the fleet
must burst to two orders of magnitude more workers — the thing a 4.5 s
cold worker start made a non-starter and the warm worker pool
(`core/worker_pool.py` fork-template zygotes) exists to make routine.
While the fleet scales, a seeded kill loop SIGKILLs random live workers
(fleet actors restart on fresh — warm — workers; the raylet's
recently-completed failover covers results dying in their buffers).

The harness asserts the elasticity contract:

  * every lease is served — each fleet actor ends up alive on a worker
    that was started either by a WARM FORK or a COLD FALLBACK spawn
    (`registered_warm + registered_cold` covers every worker; a lease
    served by neither means the pool invented a worker it can't account
    for, or dropped one);
  * every seeded kill recovers — killed actors come back and answer;
  * the load stream never wedges — every submitted call resolves as a
    result or a typed error within the deadline.

Writes a JSON artifact (burst section of ENVELOPE_r10.json) with
cold-vs-warm start counts, fork latency p50/p99, and
actors-to-first-ping for the scale-up wave. Run directly:

    python -m ray_tpu.core.burst                # full 10 -> 1000 profile
    python -m ray_tpu.core.burst --quick        # 4 -> 40 CI profile
    python -m ray_tpu.core.burst --nodes        # multi-node kill storm
    python -m ray_tpu.core.burst --nodes --quick  # CI node-storm profile

The node storm asserts the NODE failure-domain contract:

  * every seeded node kill is DETECTED — the GCS declares the node dead
    through missed heartbeats alone (no drain notify), within the
    `health_check_period_ms + health_check_timeout_ms` bound;
  * every kill is REPLACED — the autoscaler reaps the corpse at the
    provider and relaunches capacity back to `min_workers`;
  * replacement nodes onboard WARM — the register_node reply's hot env
    keys pre-spawn fork templates, and node-join-to-first-warm-lease is
    tracked as a first-class number (ENVELOPE_r12.json);
  * actors with `max_restarts` land on surviving/replacement nodes and
    every closed-loop call resolves (zero hung).
"""

from __future__ import annotations

import json
import logging
import os
import random
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclass
class BurstProfile:
    n_start: int = 10            # steady-state fleet before the burst
    n_target: int = 1000         # fleet size after the burst
    n_kills: int = 8             # seeded SIGKILLs during the scale-up
    kill_period_s: float = 1.0
    load_inflight: int = 32      # closed-loop in-flight calls on the fleet
    load_warmup_s: float = 2.0   # load runs this long before the burst
    seed: int = 0
    call_timeout_s: float = 120.0
    settle_timeout_s: float = 180.0


QUICK_PROFILE = dict(n_start=4, n_target=40, n_kills=3,
                     kill_period_s=0.5, load_inflight=8,
                     load_warmup_s=1.0, settle_timeout_s=90.0)


class _LoadGen:
    """Closed-loop call stream against the live fleet: keeps
    `inflight` calls outstanding, counts resolutions by outcome. Calls to
    killed actors resolve as typed errors (counted, not fatal) — the one
    forbidden outcome is a call that never resolves."""

    def __init__(self, actors: List, inflight: int, timeout_s: float):
        import ray_tpu

        self._ray = ray_tpu
        self._actors = actors        # shared, grows under the lock
        self._lock = threading.Lock()
        self._inflight = inflight
        self._timeout_s = timeout_s
        self._stop = threading.Event()
        self.completed = 0
        self.errored = 0
        self.hung = 0
        self._threads = [threading.Thread(target=self._run, daemon=True,
                                          name=f"burst-load-{i}")
                         for i in range(min(4, inflight))]

    def add_actors(self, actors: List) -> None:
        with self._lock:
            self._actors.extend(actors)

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def stop(self) -> Dict[str, int]:
        self._stop.set()
        # join budget covers the WORST legal iteration — every in-flight
        # get of the batch timing out serially plus one blocked submission
        # — so "hung" means a call that truly never resolved, not a thread
        # that resolved several slow typed timeouts back to back
        per_thread = max(1, self._inflight // max(1, len(self._threads)))
        budget = (per_thread + 1) * self._timeout_s + 10
        deadline = time.monotonic() + budget
        for t in self._threads:
            t.join(timeout=max(1.0, deadline - time.monotonic()))
            if t.is_alive():
                self.hung += 1
        return {"completed": self.completed, "errored": self.errored,
                "hung": self.hung}

    def _run(self) -> None:
        rng = random.Random(threading.get_ident())
        per_thread = max(1, self._inflight // max(1, len(self._threads)))
        while not self._stop.is_set():
            with self._lock:
                targets = [rng.choice(self._actors)
                           for _ in range(per_thread)]
            refs = [a.work.remote(1) for a in targets]
            for r in refs:
                try:
                    self._ray.get(r, timeout=self._timeout_s)
                    with self._lock:
                        self.completed += 1
                except Exception:
                    # typed resolution (actor died mid-kill, retry budget,
                    # timeout) — the contract only forbids silent hangs,
                    # and a worker killed mid-call surfaces here
                    with self._lock:
                        self.errored += 1


def _pool_stats() -> Dict[str, Any]:
    from ray_tpu.core.worker import current_worker

    return current_worker().raylet.call("worker_pool_stats", {}, timeout=30)


def _list_workers() -> List[Dict[str, Any]]:
    from ray_tpu.core.worker import current_worker

    try:
        return current_worker().raylet.call("list_workers", {}, timeout=30)
    except Exception:
        return []


def _idle_worker_count() -> int:
    return sum(1 for w in _list_workers() if w.get("idle"))


def _flight_record(out_path: Optional[str], violations: List[str],
                   reason: str = "violations") -> None:
    """On a failed storm, dump the flight record (last
    tracing_flight_recorder_window_s of spans + metrics snapshot) next to
    the artifact — the context the aggregate numbers lack. No-op when the
    storm passed or writes no artifact."""
    if not out_path or not violations:
        return
    from ray_tpu.util.flight_recorder import dump_flight_record

    dump_flight_record(out_path, violations, reason=reason)


def run_burst(profile: Optional[BurstProfile] = None,
              out_path: Optional[str] = None) -> Dict[str, Any]:
    """Run one burst on the CURRENT cluster (caller already init'd).
    Returns the result dict; the caller asserts on `ok` / `violations`."""
    import ray_tpu

    p = profile or BurstProfile()
    rng = random.Random(p.seed)

    @ray_tpu.remote
    class FleetWorker:
        def __init__(self):
            self._n = 0

        def work(self, x):
            self._n += 1
            return (os.getpid(), self._n)

        def ping(self):
            return os.getpid()

    def make_actors(n: int) -> List:
        return [FleetWorker.options(num_cpus=0, max_restarts=4).remote()
                for _ in range(n)]

    stats0 = _pool_stats()
    # leases may legitimately be served by workers that were ALREADY idle
    # when the burst began (e.g. envelope phases that ran before
    # --elastic): those start nothing and are still warm-pool-served
    idle0 = _idle_worker_count()
    violations: List[str] = []

    # ---- phase 1: steady-state fleet under load -------------------------
    fleet = make_actors(p.n_start)
    pids = ray_tpu.get([a.ping.remote() for a in fleet],
                       timeout=p.settle_timeout_s)
    load = _LoadGen(list(fleet), p.load_inflight, p.call_timeout_s)
    load.start()
    time.sleep(p.load_warmup_s)

    # ---- phase 2: burst to n_target under load + seeded kills -----------
    kills_done = []
    kill_stop = threading.Event()

    def killer():
        # SIGKILL a random live worker every kill_period_s — drawn from a
        # LIVE snapshot so mid-burst forks are fair game too (a recovery
        # bug specific to freshly-forked workers must not hide behind a
        # victim list frozen at burst start). The actor restarts
        # (max_restarts) on a fresh — warm — worker, and results buffered
        # in the dead process fail over via recent_done.
        while len(kills_done) < p.n_kills and not kill_stop.is_set():
            live = [w["pid"] for w in _list_workers()] or list(pids)
            victim = rng.choice(live)
            try:
                os.kill(victim, 9)
                kills_done.append(victim)
            except OSError:
                pass  # raced its own exit; snapshot refreshes next tick
            if kill_stop.wait(p.kill_period_s):
                return

    t0 = time.perf_counter()
    wave = make_actors(p.n_target - p.n_start)
    load.add_actors(wave)
    kt = threading.Thread(target=killer, daemon=True, name="burst-killer")
    kt.start()
    # first-ping with kill-recovery: the killer may SIGKILL a wave actor
    # mid-ping (typed error); the restarted actor is re-pinged until the
    # settle budget runs out — only an actor that NEVER answers violates
    wave_pids = []
    deadline = t0 + p.settle_timeout_s
    pending = [(a, a.ping.remote()) for a in wave]
    while pending and time.perf_counter() < deadline:
        retry = []
        for a, r in pending:
            try:
                wave_pids.append(ray_tpu.get(
                    r, timeout=max(0.5, deadline - time.perf_counter())))
            except Exception:
                retry.append((a, a.ping.remote()))
        pending = retry
        if pending:
            time.sleep(0.2)
    if pending:
        violations.append(
            f"{len(pending)} scale-up actors never answered first ping")
    t_wave = time.perf_counter() - t0
    # a fast scale-up must not let the chaos off the hook: the killer
    # finishes its seeded budget (bounded) before recovery is judged
    kt.join(timeout=p.n_kills * p.kill_period_s + 10)
    kill_stop.set()
    kt.join(timeout=10)

    # ---- phase 3: settle — every actor (incl. killed ones) answers ------
    recovered = 0
    t_settle0 = time.perf_counter()
    deadline = t_settle0 + p.settle_timeout_s
    for a in fleet + list(wave):
        try:
            ray_tpu.get(a.ping.remote(),
                        timeout=max(1.0, deadline - time.perf_counter()))
            recovered += 1
        except Exception as e:
            violations.append(f"actor never recovered: {type(e).__name__}")
    load_counts = load.stop()
    if load_counts["hung"]:
        violations.append(f"{load_counts['hung']} load calls never resolved")

    stats1 = _pool_stats()
    warm = stats1["registered_warm"] - stats0["registered_warm"]
    cold = stats1["registered_cold"] - stats0["registered_cold"]
    total_actors = p.n_target
    # every lease must be served by a warm fork, a cold fallback, or a
    # worker that was already idle at burst start; kills and restarts only
    # ADD workers on top of the fleet itself
    if warm + cold + idle0 < recovered:
        violations.append(
            f"workers unaccounted for: {recovered} live actors but only "
            f"{warm} warm + {cold} cold starts recorded "
            f"(+{idle0} pre-burst idle)")
    if recovered < total_actors:
        violations.append(
            f"only {recovered}/{total_actors} leases ended up served")

    result = {
        "suite": "burst-elasticity (warm worker pool chaos)",
        "profile": {
            "n_start": p.n_start, "n_target": p.n_target,
            "n_kills": p.n_kills, "seed": p.seed,
            "load_inflight": p.load_inflight,
        },
        "scale_up": {
            "actors_to_first_ping_s": round(t_wave, 2),
            "actors_per_s": round((p.n_target - p.n_start) / t_wave, 1),
            "distinct_workers": len(set(wave_pids)),
        },
        "worker_pool": {
            "warm_starts": warm, "cold_starts": cold,
            "pre_burst_idle_workers": idle0,
            "warm_fraction": round(warm / max(1, warm + cold), 3),
            "fork_p50_ms": stats1["fork_p50_ms"],
            "fork_p99_ms": stats1["fork_p99_ms"],
            "template_respawns": stats1["template_respawns"]
            - stats0["template_respawns"],
        },
        "chaos": {
            "kills": len(kills_done),
            "actors_recovered": recovered,
        },
        "load": load_counts,
        "violations": violations,
        "ok": not violations,
    }
    for a in fleet + list(wave):
        try:
            ray_tpu.kill(a)
        except Exception:
            pass
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    _flight_record(out_path, violations)
    return result


# --------------------------------------------------------------------------
# node kill storm (multi-raylet, autoscaler-driven recovery)


@dataclass
class NodeStormProfile:
    n_nodes: int = 4             # fleet nodes the autoscaler maintains
    node_cpus: float = 2.0
    actors_per_node: int = 4     # fleet capacity == actors: survivors stay
    #                              FULL, so restarts MUST land on replacements
    n_node_kills: int = 3        # seeded whole-node SIGKILLs
    kill_period_s: float = 5.0
    load_inflight: int = 16
    load_warmup_s: float = 2.0
    seed: int = 0
    call_timeout_s: float = 60.0
    settle_timeout_s: float = 120.0
    detect_timeout_s: float = 30.0
    # fast-detection knobs patched into the shared config for the run
    health_check_period_ms: int = 500
    health_check_timeout_ms: int = 3000


NODE_QUICK_PROFILE = dict(n_nodes=3, actors_per_node=3, n_node_kills=2,
                          kill_period_s=4.0, load_inflight=8,
                          load_warmup_s=1.0, settle_timeout_s=90.0)


def run_node_storm(profile: Optional[NodeStormProfile] = None,
                   out_path: Optional[str] = None) -> Dict[str, Any]:
    """One node kill storm on a fresh in-process multi-raylet cluster.
    Boots its own Cluster + FakeNodeProvider + StandardAutoscaler; the
    caller must NOT have ray_tpu initialized."""
    import ray_tpu
    from ray_tpu.autoscaler import FakeNodeProvider, NodeType, \
        StandardAutoscaler
    from ray_tpu.core.cluster import Cluster
    from ray_tpu.core.config import get_config

    p = profile or NodeStormProfile()
    rng = random.Random(p.seed)
    cfg = get_config()
    saved = (cfg.health_check_period_ms, cfg.health_check_timeout_ms)
    cfg.health_check_period_ms = p.health_check_period_ms
    cfg.health_check_timeout_ms = p.health_check_timeout_ms
    detection_bound_s = (p.health_check_period_ms
                         + p.health_check_timeout_ms) / 1000.0

    violations: List[str] = []
    removed_events: Dict[str, float] = {}   # node hexid -> t_removed
    events_lock = threading.Lock()

    def on_nodes_event(msg):
        if msg.get("event") == "removed":
            with events_lock:
                removed_events.setdefault(msg["node_id"].hex(),
                                          time.monotonic())

    # boot INSIDE the try: a failed boot must still restore the patched
    # health-check config and tear down whatever came up
    cluster = None
    provider = None
    autoscaler = None
    load: Optional[_LoadGen] = None
    try:
        cluster = Cluster()
        cluster.add_node(num_cpus=4, resources={"head": 1})
        cluster.connect()
        provider = FakeNodeProvider(cluster.gcs_address)
        fleet_cap = float(p.actors_per_node)
        autoscaler = StandardAutoscaler(
            cluster.gcs_address, provider,
            [NodeType("storm", {"CPU": p.node_cpus, "fleet": fleet_cap},
                      min_workers=p.n_nodes,
                      max_workers=p.n_nodes + p.n_node_kills + 2)],
            update_interval_s=0.25, idle_timeout_s=10_000.0)
        from ray_tpu.core.worker import current_worker

        driver = current_worker()
        driver.subscribe_channel("nodes", on_nodes_event)
        autoscaler.start()

        # ---- phase 1: the fleet forms -----------------------------------
        deadline = time.monotonic() + p.settle_timeout_s

        def alive_fleet_nodes() -> List[dict]:
            nodes = driver.gcs.call("get_all_nodes", {}, timeout=10)
            return [n for n in nodes if n.get("alive")
                    and "fleet" in n.get("resources_total", {})]

        while len(alive_fleet_nodes()) < p.n_nodes:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"fleet never formed: {len(alive_fleet_nodes())}"
                    f"/{p.n_nodes} nodes")
            time.sleep(0.2)
        initial_pids = set(provider.non_terminated_nodes())

        @ray_tpu.remote
        class FleetWorker:
            def __init__(self):
                self._n = 0

            def work(self, x):
                self._n += 1
                return (os.getpid(), self._n)

            def ping(self):
                return os.getpid()

        n_actors = p.n_nodes * p.actors_per_node
        fleet = [FleetWorker.options(num_cpus=0, max_restarts=8,
                                     resources={"fleet": 1.0}).remote()
                 for _ in range(n_actors)]
        ray_tpu.get([a.ping.remote() for a in fleet],
                    timeout=p.settle_timeout_s)
        load = _LoadGen(list(fleet), p.load_inflight, p.call_timeout_s)
        load.start()
        time.sleep(p.load_warmup_s)

        # ---- phase 2: seeded whole-node kills under load ----------------
        kills: List[Dict[str, Any]] = []
        killed_pids: set = set()
        for _ in range(p.n_node_kills):
            # a LIVE victim drawn from the provider view (replacements are
            # fair game once they joined), mapped to its cluster node id
            # BEFORE the kill so detection can be attributed. Excludes
            # nodes WE killed, not just detected ones: a corpse stays
            # provider-listed until the autoscaler reaps it, and drawing
            # it twice would record two kills for one node.
            candidates = []
            with events_lock:
                seen_dead = set(removed_events)
            for pid in provider.non_terminated_nodes():
                raylet = provider.raylet_for(pid)
                if raylet is not None and pid not in killed_pids \
                        and raylet.node_id.hex() not in seen_dead:
                    candidates.append((pid, raylet.node_id.hex()))
            if not candidates:
                violations.append("no live node left to kill")
                break
            pid, hexid = rng.choice(candidates)
            logger.warning("node storm: SIGKILLing node %s (%s)",
                           pid, hexid[:8])
            t_kill = time.monotonic()
            provider.kill_node(pid)
            killed_pids.add(pid)
            kills.append({"pid": pid, "node": hexid, "t_kill": t_kill})
            time.sleep(p.kill_period_s)

        # ---- phase 3: every kill detected, every node replaced ----------
        detect_deadline = time.monotonic() + p.detect_timeout_s
        for k in kills:
            while True:
                with events_lock:
                    t_removed = removed_events.get(k["node"])
                if t_removed is not None:
                    k["detect_s"] = round(t_removed - k["t_kill"], 3)
                    break
                if time.monotonic() > detect_deadline:
                    violations.append(
                        f"node kill {k['node'][:8]} never detected")
                    break
                time.sleep(0.1)
        detect_lat = sorted(k["detect_s"] for k in kills
                            if "detect_s" in k)
        for k in kills:
            if "detect_s" in k and k["detect_s"] > detection_bound_s * 1.5:
                violations.append(
                    f"detection of {k['node'][:8]} took {k['detect_s']}s "
                    f"(> 1.5x the {detection_bound_s}s health bound)")

        replace_deadline = time.monotonic() + p.settle_timeout_s
        while len(alive_fleet_nodes()) < p.n_nodes:
            if time.monotonic() > replace_deadline:
                violations.append(
                    f"fleet never healed: {len(alive_fleet_nodes())}"
                    f"/{p.n_nodes} alive nodes after the storm")
                break
            time.sleep(0.2)

        # ---- phase 4: settle — every actor answers, placement is live ---
        recovered = 0
        settle_deadline = time.monotonic() + p.settle_timeout_s
        last_err: Dict[int, str] = {}
        watchdog_recorder: Optional[threading.Timer] = None
        if os.environ.get("RAY_TPU_NODE_STORM_DUMP_STACKS"):
            # watchdog: if the settle phase wedges (a ping .remote() or
            # get() blocking past its budget), dump every thread so the
            # stuck frame is named instead of inferred — and the flight
            # record too, since a hang means the violations path that
            # normally dumps it may never run
            import faulthandler

            faulthandler.dump_traceback_later(
                p.settle_timeout_s * 0.8, exit=False, file=sys.stderr)
            if out_path:
                watchdog_recorder = threading.Timer(
                    p.settle_timeout_s * 0.8,
                    _flight_record, (out_path, ["settle phase wedged"],
                                     "watchdog"))
                watchdog_recorder.daemon = True
                watchdog_recorder.start()
        pending = [(a, a.ping.remote()) for a in fleet]
        while pending and time.monotonic() < settle_deadline:
            retry = []
            for a, r in pending:
                # per-get budget bounded: one wedged ref must not burn the
                # whole settle budget serially and mask the others
                per_get = min(10.0, max(
                    0.5, settle_deadline - time.monotonic()))
                try:
                    ray_tpu.get(r, timeout=per_get)
                    recovered += 1
                except Exception as e:
                    last_err[id(a)] = f"{type(e).__name__}: {e}"[:160]
                    retry.append((a, a.ping.remote()))
            pending = retry
            if pending:
                time.sleep(0.3)
        if pending:
            # "?" = no get() error was ever recorded, i.e. the ping
            # .remote() itself blocked out the settle budget (an actor
            # stuck RESTARTING blocks submission in _wait_actor_address) —
            # pull the GCS state so the failure names the stuck actor
            errs: Dict[str, int] = {}
            for a, _ in pending:
                key = last_err.get(id(a), "?")
                if key == "?":
                    try:
                        info = driver.get_actor_info(actor_id=a._actor_id)
                        key = (f"no get error; GCS state="
                               f"{info.get('state') if info else None}")
                    except Exception:
                        pass
                errs[key] = errs.get(key, 0) + 1
            violations.append(
                f"{len(pending)} actors never recovered from node kills "
                f"(last errors: {errs})")
            if os.environ.get("RAY_TPU_NODE_STORM_DUMP_STACKS"):
                import faulthandler

                faulthandler.dump_traceback(file=sys.stderr)
        if os.environ.get("RAY_TPU_NODE_STORM_DUMP_STACKS"):
            import faulthandler

            faulthandler.cancel_dump_traceback_later()
        if watchdog_recorder is not None:
            watchdog_recorder.cancel()
        load_counts = load.stop()
        load = None  # stopped; the finally must not re-join it
        if load_counts["hung"]:
            violations.append(
                f"{load_counts['hung']} load calls never resolved")

        # placement: every actor sits on an ALIVE node; count how many
        # landed on replacement (post-storm) nodes
        alive_ids = {n["node_id"] for n in
                     driver.gcs.call("get_all_nodes", {}, timeout=10)
                     if n.get("alive")}
        on_replacements = 0
        replacement_pids = [pid for pid in provider.non_terminated_nodes()
                            if pid not in initial_pids]
        replacement_ids = {provider.raylet_for(pid).node_id.binary()
                           for pid in replacement_pids
                           if provider.raylet_for(pid) is not None}
        for a in fleet:
            info = driver.get_actor_info(actor_id=a._actor_id)
            if not info or info.get("state") != "ALIVE":
                continue
            nid = info.get("node_id")
            if nid is not None and nid not in alive_ids:
                violations.append(
                    f"actor {info['actor_id']} reports a DEAD node")
            if nid in replacement_ids:
                on_replacements += 1
        if kills and not on_replacements:
            violations.append("no restarted actor landed on a replacement "
                              "node (survivors were full — placement is "
                              "wrong)")

        # ---- warm onboarding numbers ------------------------------------
        warm_joins = []
        for pid in replacement_pids:
            raylet = provider.raylet_for(pid)
            if raylet is None:
                continue
            s = raylet._worker_pool.stats()
            if s.get("join_to_first_warm_lease_s") is not None:
                warm_joins.append(s["join_to_first_warm_lease_s"])
        if replacement_pids and not warm_joins:
            violations.append("no replacement node served a warm (forked) "
                              "lease — onboarding prewarm is not working")

        gcs_node_stats = driver.gcs.call("gcs_stats", {}, timeout=10) \
            .get("node_failure", {})
        auto_stats = autoscaler.stats()
        if auto_stats["relaunches"] < len(kills):
            violations.append(
                f"autoscaler relaunched {auto_stats['relaunches']} "
                f"< {len(kills)} kills")

        result = {
            "suite": "node-kill-storm (autoscaler node failure domain)",
            "profile": {
                "n_nodes": p.n_nodes, "actors_per_node": p.actors_per_node,
                "n_node_kills": p.n_node_kills, "seed": p.seed,
                "load_inflight": p.load_inflight,
                "health_check_period_ms": p.health_check_period_ms,
                "health_check_timeout_ms": p.health_check_timeout_ms,
            },
            "chaos": {
                "node_kills": len(kills),
                "detected": len(detect_lat),
                "detection_bound_s": detection_bound_s,
                "node_death_detection_s": {
                    "p50": detect_lat[len(detect_lat) // 2]
                    if detect_lat else None,
                    "max": detect_lat[-1] if detect_lat else None,
                },
                "kills": [{"node": k["node"][:8],
                           "detect_s": k.get("detect_s")} for k in kills],
            },
            "onboarding": {
                "node_join_to_first_warm_lease_s":
                    sorted(warm_joins)[len(warm_joins) // 2]
                    if warm_joins else None,
                "per_replacement": warm_joins,
                "replacements": len(replacement_pids),
            },
            "actors": {
                "total": n_actors,
                "recovered": recovered,
                "on_replacement_nodes": on_replacements,
            },
            "autoscaler": auto_stats,
            "gcs_node_failure": gcs_node_stats,
            "load": load_counts,
            "violations": violations,
            "ok": not violations,
        }
        for a in fleet:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        if out_path:
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2)
                f.write("\n")
        _flight_record(out_path, violations)
        return result
    finally:
        if load is not None:
            # an exception escaped mid-storm: silence the load threads
            # BEFORE tearing the cluster down under them
            try:
                load.stop()
            except Exception:
                pass
        if autoscaler is not None:
            try:
                autoscaler.stop()
            except Exception:
                pass
        if provider is not None:
            for pid in provider.non_terminated_nodes():
                try:
                    provider.terminate_node(pid)
                except Exception:
                    pass
        if cluster is not None:
            try:
                cluster.shutdown()
            except Exception:
                logger.exception("node storm cluster shutdown failed")
        cfg.health_check_period_ms, cfg.health_check_timeout_ms = saved


# --------------------------------------------------------------------------
# partition-heal storm (peer-scoped partitions, incarnation fencing,
# gray-failure quarantine — the partition failure domain end to end)


@dataclass
class PartitionStormProfile:
    n_nodes: int = 4             # autoscaler-maintained fleet nodes
    node_cpus: float = 2.0
    actors_per_node: int = 3     # capacity == actors: restarts NEED the
    #                              replacement, survivors stay full
    n_partitions: int = 3        # death-bound partition+heal cycles
    partition_hold_s: float = 6.0   # > death bound: node declared dead,
    #                                 actors restarted, THEN the heal
    quarantine_cycles: int = 1   # short partitions that must NOT kill
    quarantine_hold_s: float = 1.6  # inside (quarantine, death) window
    head_in_minority: bool = True   # final cycle cuts the head from the
    #                                 store side: PR 11's lease fencing
    #                                 promotes the standby
    load_inflight: int = 12
    load_warmup_s: float = 1.5
    seed: int = 0
    call_timeout_s: float = 60.0
    settle_timeout_s: float = 120.0
    # fast failure-detection knobs patched into the shared config
    health_check_period_ms: int = 500
    health_check_timeout_ms: int = 3000
    node_quarantine_timeout_ms: int = 1200
    head_lease_ttl_s: float = 1.5


PARTITION_QUICK_PROFILE = dict(n_nodes=3, actors_per_node=2,
                               n_partitions=2, partition_hold_s=5.0,
                               quarantine_cycles=1, load_inflight=8,
                               load_warmup_s=1.0, settle_timeout_s=90.0)


def run_partition_storm(profile: Optional[PartitionStormProfile] = None,
                        out_path: Optional[str] = None) -> Dict[str, Any]:
    """One partition-heal storm on a fresh multi-raylet cluster.

    Per death cycle: blackhole a minority {one fleet node} from the
    majority {head + rest + store} mid-load, with the provider's
    termination of the unreachable host HELD (the cloud API "deletes" a VM
    it cannot reach — a zombie raylet survives the autoscaler's reap).
    Assert: the node is QUARANTINED before the death bound, declared dead
    AT the bound, its named actors restart (incarnation+1) on the
    replacement the autoscaler launches; then HEAL and assert convergence
    — the zombie is fenced on its first heartbeat, kills its superseded
    workers, rejoins as a fresh node; every named actor answers from
    exactly ONE live incarnation (a deliberately stale handle probe must
    be served by the NEW instance, never the old one); zero hung calls;
    relaunches never exceed true deaths (no double replacement).

    Quarantine cycles hold the partition INSIDE the death bound: the node
    must be quarantined (no new dispatch) and then recover with its actors
    intact — zero deaths, zero relaunches, same pids.

    The final cycle puts the HEAD in the minority (cut from the store
    side): its lease renewals starve, the PR-11 standby promotes via the
    epoch CAS, the old head self-fences through the existing lease path,
    and the healed fleet re-adopts the new head.
    """
    import ray_tpu
    from ray_tpu.autoscaler import FakeNodeProvider, NodeType, \
        StandardAutoscaler
    from ray_tpu.core import rpc
    from ray_tpu.core.cluster import Cluster
    from ray_tpu.core.config import get_config

    p = profile or PartitionStormProfile()
    rng = random.Random(p.seed)
    cfg = get_config()
    saved = (cfg.health_check_period_ms, cfg.health_check_timeout_ms,
             cfg.node_quarantine_timeout_ms, cfg.head_lease_ttl_s,
             cfg.gcs_address_file)
    cfg.health_check_period_ms = p.health_check_period_ms
    cfg.health_check_timeout_ms = p.health_check_timeout_ms
    cfg.node_quarantine_timeout_ms = p.node_quarantine_timeout_ms
    cfg.head_lease_ttl_s = p.head_lease_ttl_s
    import tempfile

    # the address file lets the autoscaler, raylets and workers follow the
    # promoted head after the head-in-minority cycle
    addr_file = os.path.join(tempfile.mkdtemp(prefix="rtpu-pstorm-"),
                             "gcs_address")
    cfg.gcs_address_file = addr_file
    death_bound_s = (p.health_check_period_ms
                     + p.health_check_timeout_ms) / 1000.0

    violations: List[str] = []
    cycles: List[Dict[str, Any]] = []
    cluster = provider = autoscaler = standby = None
    load: Optional[_LoadGen] = None
    old_head = None
    zombies: List[Any] = []
    inj = rpc.install_fault_injector("", seed=p.seed)
    try:
        cluster = Cluster(
            snapshot_uri=f"memory://partition-storm-{os.getpid()}")
        # tight snapshot cadence: the standby promotes from the tailed
        # snapshot, and the failure-domain counters it restores should be
        # near-live, not up to 5 s stale
        cluster.gcs._snapshot_interval_s = 0.5
        head_raylet = cluster.add_node(num_cpus=4, resources={"head": 1})
        cluster.connect()
        from ray_tpu.core.worker import current_worker

        driver = current_worker()
        provider = FakeNodeProvider(cluster.gcs_address)
        fleet_cap = float(p.actors_per_node)
        autoscaler = StandardAutoscaler(
            cluster.gcs_address, provider,
            [NodeType("storm", {"CPU": p.node_cpus, "fleet": fleet_cap},
                      min_workers=p.n_nodes,
                      max_workers=p.n_nodes + p.n_partitions + 3)],
            update_interval_s=0.25, idle_timeout_s=10_000.0)
        autoscaler.start()
        if p.head_in_minority:
            standby = cluster.start_standby()

        def node_failure_stats() -> Dict[str, Any]:
            return driver.gcs.call("gcs_stats", {},
                                   timeout=10)["node_failure"]

        def alive_fleet_nodes() -> List[dict]:
            nodes = driver.gcs.call("get_all_nodes", {}, timeout=10)
            return [n for n in nodes if n.get("alive")
                    and "fleet" in n.get("resources_total", {})]

        deadline = time.monotonic() + p.settle_timeout_s
        while len(alive_fleet_nodes()) < p.n_nodes:
            if time.monotonic() > deadline:
                raise RuntimeError("fleet never formed")
            time.sleep(0.2)

        @ray_tpu.remote
        class FleetWorker:
            def __init__(self):
                self._n = 0

            def work(self, x):
                self._n += 1
                return self.ping()

            def ping(self):
                from ray_tpu.core.worker import current_worker as _cw

                return (os.getpid(), _cw()._actor_incarnation)

        n_actors = p.n_nodes * p.actors_per_node
        fleet = [FleetWorker.options(num_cpus=0, max_restarts=16,
                                     name=f"storm-{i}",
                                     resources={"fleet": 1.0}).remote()
                 for i in range(n_actors)]
        ray_tpu.get([a.ping.remote() for a in fleet],
                    timeout=p.settle_timeout_s)
        load = _LoadGen(list(fleet), p.load_inflight, p.call_timeout_s)
        load.start()
        time.sleep(p.load_warmup_s)

        current_head = cluster.gcs.address

        def majority_for(minority: set) -> set:
            members = {current_head, head_raylet.address, "store"}
            for pid in provider.non_terminated_nodes():
                raylet = provider.raylet_for(pid)
                if raylet is not None and raylet.address not in minority:
                    members.add(raylet.address)
            return members - minority

        def await_counter(read, key, floor, timeout, what) -> Optional[float]:
            t0 = time.monotonic()
            while time.monotonic() - t0 < timeout:
                try:
                    if read()[key] >= floor:
                        return time.monotonic() - t0
                except Exception:
                    pass
                time.sleep(0.1)
            violations.append(f"{what} never observed ({key} < {floor})")
            return None

        def actor_infos():
            return {a: driver.get_actor_info(actor_id=a._actor_id)
                    for a in fleet}

        # ---------------- death-bound partition + heal cycles ------------
        for ci in range(p.n_partitions):
            candidates = []
            for pid in provider.non_terminated_nodes():
                raylet = provider.raylet_for(pid)
                if raylet is not None:
                    candidates.append((pid, raylet))
            if not candidates:
                violations.append("no fleet node left to partition")
                break
            victim_pid, victim = rng.choice(candidates)
            victim_node_id = victim.node_id.binary()
            infos0 = actor_infos()
            victims = [(a, i) for a, i in infos0.items()
                       if i and i.get("node_id") == victim_node_id
                       and i.get("state") == "ALIVE"]
            probe = None
            if victims:
                a, i = victims[0]
                try:
                    old_pid, _old_inc = ray_tpu.get(a.ping.remote(),
                                                    timeout=20)
                    probe = (a, i["address"], i["incarnation"], old_pid)
                except Exception:
                    probe = None
            nf0 = node_failure_stats()
            auto0 = autoscaler.stats()
            minority = {victim.address}
            inj.define_group("minority", minority)
            inj.define_group("majority", majority_for(minority))
            provider.hold_termination(victim_pid)
            logger.warning("partition storm: cycle %d cuts node %s (%s) "
                           "from the majority", ci, victim_pid,
                           victim.node_id.hex()[:8])
            t_cut = time.monotonic()
            inj.partition("minority", "majority")

            # one poll loop, both timestamps anchored at the cut: the
            # quarantine must be OBSERVED strictly before the death (the
            # gray-failure ramp precedes the crash-stop declaration)
            t_quarantine = t_death = None
            poll_deadline = time.monotonic() + death_bound_s * 3
            while time.monotonic() < poll_deadline:
                try:
                    nf = node_failure_stats()
                except Exception:
                    time.sleep(0.1)
                    continue
                now = time.monotonic() - t_cut
                if t_quarantine is None and nf["quarantines_total"] \
                        >= nf0["quarantines_total"] + 1:
                    t_quarantine = now
                if nf["deaths_total"] >= nf0["deaths_total"] + 1:
                    t_death = now
                    break
                time.sleep(0.1)
            if t_death is None:
                violations.append(
                    f"cycle {ci}: partitioned node never declared dead")
            if t_quarantine is None:
                violations.append(
                    f"cycle {ci}: node was never quarantined before death")
            elif t_death is not None and t_quarantine >= t_death:
                violations.append(
                    f"cycle {ci}: quarantine ({t_quarantine:.2f}s) did not "
                    f"precede death ({t_death:.2f}s)")
            # hold the partition out, then heal
            remaining = p.partition_hold_s - (time.monotonic() - t_cut)
            if remaining > 0:
                time.sleep(remaining)
            t_heal = time.monotonic()
            inj.heal()
            zombie = provider.release_zombie(victim_pid)
            if zombie is not None:
                zombies.append(zombie)
            elif t_death is not None:
                violations.append(
                    f"cycle {ci}: no zombie survived the reap (terminate "
                    f"hold did not engage)")

            # ---- convergence ----
            await_counter(node_failure_stats, "fences_total",
                          nf0["fences_total"] + 1, p.settle_timeout_s,
                          f"cycle {ci}: zombie fence")
            await_counter(lambda: autoscaler.stats(), "relaunches",
                          auto0["relaunches"] + 1, p.settle_timeout_s,
                          f"cycle {ci}: autoscaler relaunch")
            # zombie rejoined as a FRESH node (same address, new identity)
            if zombie is not None:
                t0 = time.monotonic()
                rejoined = False
                while time.monotonic() - t0 < p.settle_timeout_s:
                    for n in alive_fleet_nodes():
                        if n["address"] == zombie.address \
                                and n["node_id"] != victim_node_id:
                            rejoined = True
                            break
                    if rejoined:
                        break
                    time.sleep(0.2)
                if not rejoined:
                    violations.append(
                        f"cycle {ci}: fenced node never rejoined fresh")
            # every victim actor ALIVE again with a bumped incarnation and
            # answering from exactly ONE live instance
            converge_deadline = time.monotonic() + p.settle_timeout_s
            for a, i0 in victims:
                ok = False
                while time.monotonic() < converge_deadline:
                    info = driver.get_actor_info(actor_id=a._actor_id)
                    if info and info["state"] == "ALIVE" \
                            and info["incarnation"] > i0["incarnation"]:
                        ok = True
                        break
                    time.sleep(0.2)
                if not ok:
                    violations.append(
                        f"cycle {ci}: actor {i0['actor_id']} never came "
                        f"back with a new incarnation: {info}")
                    continue
                pids = set()
                for _ in range(3):
                    try:
                        rpid, rinc = ray_tpu.get(
                            a.ping.remote(),
                            timeout=max(1.0, converge_deadline
                                        - time.monotonic()))
                        pids.add(rpid)
                        if rinc != info["incarnation"]:
                            violations.append(
                                f"cycle {ci}: answer from incarnation "
                                f"{rinc} != live {info['incarnation']} — "
                                f"duplicate instance")
                    except Exception as e:
                        violations.append(
                            f"cycle {ci}: converged actor stopped "
                            f"answering: {type(e).__name__}")
                        break
                if len(pids) > 1:
                    violations.append(
                        f"cycle {ci}: named actor answered from "
                        f"{len(pids)} pids — duplicate live instances")
            # stale-handle probe: force the pre-partition (address,
            # incarnation) back into the driver's cache and call — the
            # fence must route it to the NEW instance (the old one is
            # dead/fenced and can never answer)
            probe_ok = None
            if probe is not None:
                a, old_addr, old_inc, old_pid = probe
                with driver._actor_seq_lock:
                    driver._actor_addresses[a._actor_id] = old_addr
                    driver._actor_incarnations[a._actor_id] = old_inc
                try:
                    rpid, rinc = ray_tpu.get(a.ping.remote(), timeout=30)
                    probe_ok = rpid != old_pid
                    if not probe_ok:
                        violations.append(
                            f"cycle {ci}: STALE instance answered the "
                            f"stale-handle probe (pid {rpid})")
                except Exception as e:
                    probe_ok = False
                    violations.append(
                        f"cycle {ci}: stale-handle probe never converged: "
                        f"{type(e).__name__}: {e}"[:200])
            t_converged = time.monotonic()
            cycles.append({
                "kind": "death", "node": victim.node_id.hex()[:8],
                "quarantine_s": round(t_quarantine, 3)
                if t_quarantine is not None else None,
                "death_detect_s": round(t_death, 3)
                if t_death is not None else None,
                "heal_to_convergence_s": round(t_converged - t_heal, 3),
                "stale_handle_probe_served_by_new": probe_ok,
            })

        # ---------------- quarantine-and-recover cycles ------------------
        for ci in range(p.quarantine_cycles):
            infos0 = actor_infos()
            hosting = {i["node_id"] for i in infos0.values()
                       if i and i.get("state") == "ALIVE"
                       and i.get("node_id")}
            candidates = [(pid, provider.raylet_for(pid))
                          for pid in provider.non_terminated_nodes()
                          if provider.raylet_for(pid) is not None]
            if not candidates:
                violations.append("no fleet node left to quarantine")
                break
            # prefer a node that HOSTS actors: the point is proving they
            # survive quarantine+recovery with zero relaunches
            hosting_candidates = [(pid, r) for pid, r in candidates
                                  if r.node_id.binary() in hosting]
            victim_pid, victim = rng.choice(hosting_candidates
                                            or candidates)
            victim_node_id = victim.node_id.binary()
            held = {i["actor_id"]: i["incarnation"]
                    for i in infos0.values()
                    if i and i.get("node_id") == victim_node_id}
            nf0 = node_failure_stats()
            auto0 = autoscaler.stats()
            minority = {victim.address}
            inj.define_group("minority", minority)
            inj.define_group("majority", majority_for(minority))
            logger.warning("partition storm: quarantine cycle grays out "
                           "node %s", victim.node_id.hex()[:8])
            t_cut = time.monotonic()
            inj.partition("minority", "majority")
            t_q = await_counter(
                node_failure_stats, "quarantines_total",
                nf0["quarantines_total"] + 1, death_bound_s * 2,
                "quarantine cycle: node never quarantined")
            remaining = p.quarantine_hold_s - (time.monotonic() - t_cut)
            if remaining > 0:
                time.sleep(remaining)
            t_heal = time.monotonic()
            inj.heal()
            t_rec = await_counter(
                node_failure_stats, "quarantine_recoveries_total",
                nf0["quarantine_recoveries_total"] + 1, death_bound_s * 2,
                "quarantine cycle: node never recovered")
            nf1 = node_failure_stats()
            auto1 = autoscaler.stats()
            if nf1["deaths_total"] != nf0["deaths_total"]:
                violations.append("quarantine cycle: node was declared "
                                  "DEAD inside the quarantine window")
            if auto1["relaunches"] != auto0["relaunches"]:
                violations.append("quarantine cycle: autoscaler replaced a "
                                  "quarantined (recoverable) node")
            kept = 0
            for aid, inc in held.items():
                info = driver.gcs.call("get_actor_info",
                                       {"actor_id": aid}, timeout=10)
                if info and info["state"] == "ALIVE" \
                        and info["incarnation"] == inc:
                    kept += 1
                else:
                    violations.append(
                        f"quarantine cycle: actor {aid} did not keep its "
                        f"incarnation across recovery: {info}")
            if not held:
                violations.append("quarantine cycle: victim hosted no "
                                  "actors — nothing proven")
            cycles.append({
                "kind": "quarantine", "node": victim.node_id.hex()[:8],
                "quarantine_s": round(t_q, 3) if t_q is not None else None,
                # await started at the heal: this IS heal->recovery
                "heal_to_recovery_s": round(t_rec, 3)
                if t_rec is not None else None,
                "actors_kept": kept, "actors_held": len(held),
            })

        # ---------------- head-in-minority cycle -------------------------
        if p.head_in_minority and standby is not None:
            stats0 = driver.gcs.call("gcs_stats", {}, timeout=10)
            epoch0 = stats0["fence_epoch"]
            old_head = cluster.gcs
            minority = {current_head}
            inj.define_group("minority", minority)
            inj.define_group("majority", majority_for(minority))
            logger.warning("partition storm: head-in-minority cycle cuts "
                           "the head %s from the store side", current_head)
            t_cut = time.monotonic()
            inj.partition("minority", "majority")
            promoted = standby.wait_promoted(p.settle_timeout_s)
            if promoted is None:
                violations.append("head-in-minority: standby never "
                                  "promoted (lease starvation failed)")
            t_heal = time.monotonic()
            inj.heal()
            if promoted is not None:
                cluster.adopt_promoted(standby)
                current_head = promoted.address
                # the old head self-fences via the existing lease path
                # (reads the bumped epoch) once healed
                t0 = time.monotonic()
                while not old_head._fenced.is_set() \
                        and time.monotonic() - t0 < p.settle_timeout_s:
                    time.sleep(0.1)
                if not old_head._fenced.is_set():
                    violations.append("head-in-minority: old head never "
                                      "self-fenced after the heal")
                # the fleet re-adopts the promoted head
                stats1: Dict[str, Any] = {}
                t0 = time.monotonic()
                while time.monotonic() - t0 < p.settle_timeout_s:
                    try:
                        stats1 = driver.gcs.call("gcs_stats", {},
                                                 timeout=5)
                        if stats1["fence_epoch"] > epoch0 \
                                and stats1["nodes_alive"] >= p.n_nodes:
                            break
                    except Exception:
                        pass
                    time.sleep(0.2)
                else:
                    violations.append("head-in-minority: fleet never "
                                      "re-adopted the promoted head")
                cycles.append({
                    "kind": "head_in_minority",
                    "epoch": f"{epoch0}->{stats1.get('fence_epoch')}",
                    "promotion": stats1.get("promotion"),
                    "heal_to_convergence_s":
                        round(time.monotonic() - t_heal, 3),
                })

        # ---------------- final convergence sweep ------------------------
        final_deadline = time.monotonic() + p.settle_timeout_s
        for idx, a in enumerate(fleet):
            try:
                named = ray_tpu.get_actor(f"storm-{idx}")
                rpid, rinc = ray_tpu.get(
                    named.ping.remote(),
                    timeout=max(1.0, final_deadline - time.monotonic()))
                info = driver.get_actor_info(actor_id=a._actor_id)
                if info is None or rinc != info["incarnation"]:
                    violations.append(
                        f"final: storm-{idx} answered from incarnation "
                        f"{rinc}, GCS records "
                        f"{info and info['incarnation']}")
            except Exception as e:
                violations.append(
                    f"final: storm-{idx} unresolvable: "
                    f"{type(e).__name__}: {e}"[:160])
        load_counts = load.stop()
        load = None
        if load_counts["hung"]:
            violations.append(
                f"{load_counts['hung']} load calls never resolved")
        nf_final = node_failure_stats()
        auto_final = autoscaler.stats()
        if auto_final["relaunches"] > nf_final["deaths_total"]:
            violations.append(
                f"autoscaler double-replaced: {auto_final['relaunches']} "
                f"relaunches > {nf_final['deaths_total']} true deaths")

        result = {
            "suite": "partition-heal storm (partition failure domain)",
            "profile": {
                "n_nodes": p.n_nodes, "actors_per_node": p.actors_per_node,
                "n_partitions": p.n_partitions,
                "quarantine_cycles": p.quarantine_cycles,
                "head_in_minority": p.head_in_minority, "seed": p.seed,
                "health_check_period_ms": p.health_check_period_ms,
                "health_check_timeout_ms": p.health_check_timeout_ms,
                "node_quarantine_timeout_ms": p.node_quarantine_timeout_ms,
                "death_bound_s": death_bound_s,
            },
            "cycles": cycles,
            "counters": {
                "deaths_total": nf_final["deaths_total"],
                "quarantines_total": nf_final["quarantines_total"],
                "quarantine_recoveries_total":
                    nf_final["quarantine_recoveries_total"],
                "fences_total": nf_final["fences_total"],
                "stale_incarnation_rejections":
                    nf_final["stale_incarnation_rejections"],
                "driver_stale_reply_rejections":
                    driver.stale_reply_rejections,
                "relaunches": auto_final["relaunches"],
                "partition_drops": inj.stats["partition"],
            },
            "heal_to_convergence_s": {
                "max": max((c["heal_to_convergence_s"] for c in cycles
                            if c.get("heal_to_convergence_s") is not None),
                           default=None),
                "per_cycle": [c.get("heal_to_convergence_s")
                              for c in cycles],
            },
            "load": load_counts,
            "violations": violations,
            "ok": not violations,
        }
        for a in fleet:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        if out_path:
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2)
                f.write("\n")
        _flight_record(out_path, violations)
        return result
    finally:
        if load is not None:
            try:
                load.stop()
            except Exception:
                pass
        try:
            inj.heal()
        except Exception:
            pass
        rpc.clear_fault_injector()
        for z in zombies:
            try:
                z.stop()
            except Exception:
                pass
        if autoscaler is not None:
            try:
                autoscaler.stop()
            except Exception:
                pass
        if provider is not None:
            for pid in provider.non_terminated_nodes():
                try:
                    provider.terminate_node(pid)
                except Exception:
                    pass
        if old_head is not None:
            try:
                old_head.kill()
            except Exception:
                pass
        if cluster is not None:
            try:
                cluster.shutdown()
            except Exception:
                logger.exception("partition storm cluster shutdown failed")
        (cfg.health_check_period_ms, cfg.health_check_timeout_ms,
         cfg.node_quarantine_timeout_ms, cfg.head_lease_ttl_s,
         cfg.gcs_address_file) = saved


def _partition_storm_main(args) -> int:
    kw: Dict[str, Any] = dict(PARTITION_QUICK_PROFILE) if args.quick else {}
    kw["seed"] = args.seed
    p = PartitionStormProfile(**kw)
    result = run_partition_storm(p, out_path=args.json)
    print(json.dumps(result, indent=2))
    c = result["counters"]
    print(f"[partition-storm] seed={p.seed} nodes={p.n_nodes} "
          f"partitions={p.n_partitions}+{p.quarantine_cycles}q"
          f"{'+head' if p.head_in_minority else ''} | "
          f"deaths={c['deaths_total']} quarantines={c['quarantines_total']} "
          f"(recovered {c['quarantine_recoveries_total']}) "
          f"fences={c['fences_total']} relaunches={c['relaunches']} "
          f"stale_rejections={c['stale_incarnation_rejections']} | "
          f"heal->convergence max "
          f"{result['heal_to_convergence_s']['max']}s | "
          f"load={result['load']}", file=sys.stderr)
    if not result["ok"]:
        print("[partition-storm] VIOLATIONS:", file=sys.stderr)
        for v in result["violations"]:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


def _node_storm_main(args) -> int:
    kw: Dict[str, Any] = dict(NODE_QUICK_PROFILE) if args.quick else {}
    kw["seed"] = args.seed
    if args.kills is not None:
        kw["n_node_kills"] = args.kills
    p = NodeStormProfile(**kw)
    result = run_node_storm(p, out_path=args.json)
    print(json.dumps(result, indent=2))
    c, o = result["chaos"], result["onboarding"]
    print(f"[node-storm] seed={p.seed} nodes={p.n_nodes} "
          f"kills={c['node_kills']} detected={c['detected']} "
          f"(p50 {c['node_death_detection_s']['p50']}s, bound "
          f"{c['detection_bound_s']}s) | replacements={o['replacements']} "
          f"join->first-warm-lease={o['node_join_to_first_warm_lease_s']}s "
          f"| actors recovered={result['actors']['recovered']}"
          f"/{result['actors']['total']} "
          f"(on replacements: {result['actors']['on_replacement_nodes']}) "
          f"| load={result['load']}", file=sys.stderr)
    if not result["ok"]:
        print("[node-storm] VIOLATIONS:", file=sys.stderr)
        for v in result["violations"]:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


# --------------------------------------------------------------------------
# cross-node worker burst: the worker-burst axis composed with --nodes


@dataclass
class CrossNodeBurstProfile:
    n_nodes: int = 4             # autoscaler-maintained fleet nodes
    node_cpus: float = 4.0
    n_start: int = 10
    n_target: int = 1000         # burst ACROSS the node fleet
    load_inflight: int = 32
    load_warmup_s: float = 2.0
    seed: int = 0
    call_timeout_s: float = 120.0
    settle_timeout_s: float = 300.0


CROSS_QUICK_PROFILE = dict(n_nodes=3, n_start=4, n_target=40,
                           load_inflight=8, load_warmup_s=1.0,
                           settle_timeout_s=120.0)


def run_cross_node_burst(profile: Optional[CrossNodeBurstProfile] = None,
                         out_path: Optional[str] = None) -> Dict[str, Any]:
    """Burst the worker fleet n_start -> n_target ACROSS a multi-raylet
    cluster (ROADMAP item 1 leftover: compose `--nodes` with the
    worker-burst axis). SPREAD-scheduled actors under closed-loop load;
    asserts every actor answers, the wave genuinely lands on multiple
    nodes, every lease is served by a warm fork or a cold fallback
    (aggregated across EVERY raylet's pool), and no load call hangs."""
    import ray_tpu
    from ray_tpu.autoscaler import FakeNodeProvider, NodeType, \
        StandardAutoscaler
    from ray_tpu.core.cluster import Cluster
    from ray_tpu.core.task_spec import SchedulingStrategy

    p = profile or CrossNodeBurstProfile()
    violations: List[str] = []
    cluster = provider = autoscaler = None
    load: Optional[_LoadGen] = None
    try:
        cluster = Cluster()
        head_raylet = cluster.add_node(num_cpus=4, resources={"head": 1})
        cluster.connect()
        from ray_tpu.core.worker import current_worker

        driver = current_worker()
        provider = FakeNodeProvider(cluster.gcs_address)
        # per-node "slot" capacity: a REAL consumable resource (zero-cpu
        # actors leave utilization flat, which degenerates SPREAD onto one
        # node) sized so the full burst fits with ~25% slack per node
        slot_cap = float(-(-p.n_target * 5 // (p.n_nodes * 4)))
        autoscaler = StandardAutoscaler(
            cluster.gcs_address, provider,
            [NodeType("burst", {"CPU": p.node_cpus, "slot": slot_cap},
                      min_workers=p.n_nodes, max_workers=p.n_nodes + 2)],
            update_interval_s=0.25, idle_timeout_s=10_000.0)
        autoscaler.start()

        def fleet_raylets():
            out = [head_raylet]
            for pid in provider.non_terminated_nodes():
                r = provider.raylet_for(pid)
                if r is not None:
                    out.append(r)
            return out

        deadline = time.monotonic() + p.settle_timeout_s
        while len(provider.non_terminated_nodes()) < p.n_nodes:
            if time.monotonic() > deadline:
                raise RuntimeError("node fleet never formed")
            time.sleep(0.2)

        def pool_totals() -> Dict[str, int]:
            tot = {"registered_warm": 0, "registered_cold": 0}
            for r in fleet_raylets():
                s = r._worker_pool.stats()
                tot["registered_warm"] += s["registered_warm"]
                tot["registered_cold"] += s["registered_cold"]
            return tot

        def idle_total() -> int:
            n = 0
            for r in fleet_raylets():
                with r._lock:
                    n += sum(len(pool) for pool in r._idle_pools.values())
            return n

        @ray_tpu.remote
        class FleetWorker:
            def __init__(self):
                self._n = 0

            def work(self, x):
                self._n += 1
                return (os.getpid(), self._n)

            def ping(self):
                return os.getpid()

        def make_actors(n: int) -> List:
            return [FleetWorker.options(
                num_cpus=0, max_restarts=4, resources={"slot": 1.0},
                scheduling_strategy=SchedulingStrategy(
                    name="SPREAD")).remote() for _ in range(n)]

        stats0 = pool_totals()
        idle0 = idle_total()
        fleet = make_actors(p.n_start)
        ray_tpu.get([a.ping.remote() for a in fleet],
                    timeout=p.settle_timeout_s)
        load = _LoadGen(list(fleet), p.load_inflight, p.call_timeout_s)
        load.start()
        time.sleep(p.load_warmup_s)

        t0 = time.perf_counter()
        wave = make_actors(p.n_target - p.n_start)
        load.add_actors(wave)
        wave_pids = []
        deadline = t0 + p.settle_timeout_s
        pending = [(a, a.ping.remote()) for a in wave]
        while pending and time.perf_counter() < deadline:
            retry = []
            for a, r in pending:
                try:
                    wave_pids.append(ray_tpu.get(
                        r, timeout=max(0.5,
                                       deadline - time.perf_counter())))
                except Exception:
                    retry.append((a, a.ping.remote()))
            pending = retry
            if pending:
                time.sleep(0.2)
        if pending:
            violations.append(f"{len(pending)} cross-node scale-up actors "
                              f"never answered first ping")
        t_wave = time.perf_counter() - t0
        load_counts = load.stop()
        load = None
        if load_counts["hung"]:
            violations.append(
                f"{load_counts['hung']} load calls never resolved")

        # distribution: the wave must genuinely land across nodes
        nodes_used = set()
        for a in fleet + list(wave):
            info = driver.get_actor_info(actor_id=a._actor_id)
            if info and info.get("node_id"):
                nodes_used.add(info["node_id"])
        if len(nodes_used) < min(p.n_nodes, 2):
            violations.append(
                f"burst landed on only {len(nodes_used)} node(s) of "
                f"{p.n_nodes + 1} — not a cross-node burst")
        stats1 = pool_totals()
        warm = stats1["registered_warm"] - stats0["registered_warm"]
        cold = stats1["registered_cold"] - stats0["registered_cold"]
        answered = p.n_target - len(pending)
        if warm + cold + idle0 < answered:
            violations.append(
                f"workers unaccounted for across nodes: {answered} actors "
                f"but only {warm} warm + {cold} cold starts "
                f"(+{idle0} pre-burst idle)")

        result = {
            "suite": "cross-node worker burst (--nodes x worker-burst)",
            "profile": {"n_nodes": p.n_nodes, "n_start": p.n_start,
                        "n_target": p.n_target, "seed": p.seed,
                        "load_inflight": p.load_inflight},
            "scale_up": {
                "actors_to_first_ping_s": round(t_wave, 2),
                "actors_per_s": round((p.n_target - p.n_start)
                                      / max(t_wave, 1e-9), 1),
                "distinct_workers": len(set(wave_pids)),
                "nodes_used": len(nodes_used),
            },
            "worker_pool": {"warm_starts": warm, "cold_starts": cold,
                            "pre_burst_idle_workers": idle0,
                            "warm_fraction":
                                round(warm / max(1, warm + cold), 3)},
            "load": load_counts,
            "violations": violations,
            "ok": not violations,
        }
        for a in fleet + list(wave):
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        if out_path:
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2)
                f.write("\n")
        _flight_record(out_path, violations)
        return result
    finally:
        if load is not None:
            try:
                load.stop()
            except Exception:
                pass
        if autoscaler is not None:
            try:
                autoscaler.stop()
            except Exception:
                pass
        if provider is not None:
            for pid in provider.non_terminated_nodes():
                try:
                    provider.terminate_node(pid)
                except Exception:
                    pass
        if cluster is not None:
            try:
                cluster.shutdown()
            except Exception:
                logger.exception("cross-node burst cluster shutdown failed")


def _cross_node_burst_main(args) -> int:
    kw: Dict[str, Any] = dict(CROSS_QUICK_PROFILE) if args.quick else {}
    kw["seed"] = args.seed
    if args.start is not None:
        kw["n_start"] = args.start
    if args.target is not None:
        kw["n_target"] = args.target
    p = CrossNodeBurstProfile(**kw)
    result = run_cross_node_burst(p, out_path=args.json)
    print(json.dumps(result, indent=2))
    su, wp = result["scale_up"], result["worker_pool"]
    print(f"[cross-burst] seed={p.seed} {p.n_start} -> {p.n_target} "
          f"workers across {su['nodes_used']} nodes in "
          f"{su['actors_to_first_ping_s']}s | warm={wp['warm_starts']} "
          f"cold={wp['cold_starts']} (warm fraction {wp['warm_fraction']}) "
          f"| load={result['load']}", file=sys.stderr)
    if not result["ok"]:
        print("[cross-burst] VIOLATIONS:", file=sys.stderr)
        for v in result["violations"]:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="scaled-down CI profile (4 -> 40 workers)")
    ap.add_argument("--nodes", action="store_true",
                    help="multi-raylet NODE kill storm (autoscaler-driven "
                         "replacement + warm onboarding); with --target: "
                         "worker burst ACROSS the node fleet instead")
    ap.add_argument("--partition", action="store_true",
                    help="partition-heal storm: peer-scoped partitions, "
                         "gray-failure quarantine, incarnation fencing, "
                         "head-in-minority lease fencing")
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get(
                        "RAY_TPU_FAULT_INJECTION_SEED", "0")))
    ap.add_argument("--start", type=int, default=None)
    ap.add_argument("--target", type=int, default=None)
    ap.add_argument("--kills", type=int, default=None)
    ap.add_argument("--json", default=None, help="write the result here")
    args = ap.parse_args(argv)

    if args.partition:
        return _partition_storm_main(args)
    if args.nodes and args.target is not None:
        return _cross_node_burst_main(args)
    if args.nodes:
        return _node_storm_main(args)

    kw: Dict[str, Any] = dict(QUICK_PROFILE) if args.quick else {}
    kw["seed"] = args.seed
    if args.start is not None:
        kw["n_start"] = args.start
    if args.target is not None:
        kw["n_target"] = args.target
    if args.kills is not None:
        kw["n_kills"] = args.kills
    p = BurstProfile(**kw)

    import ray_tpu

    # enough CPU headroom that the fleet (num_cpus=0 actors) and the load
    # stream never contend on scheduler admission
    ray_tpu.init(num_cpus=8)
    try:
        result = run_burst(p, out_path=args.json)
    finally:
        ray_tpu.shutdown()

    print(json.dumps(result, indent=2))
    wp, su = result["worker_pool"], result["scale_up"]
    print(f"[burst] seed={p.seed} {p.n_start} -> {p.n_target} workers in "
          f"{su['actors_to_first_ping_s']}s | warm={wp['warm_starts']} "
          f"cold={wp['cold_starts']} (warm fraction "
          f"{wp['warm_fraction']}) fork p50/p99 = {wp['fork_p50_ms']}/"
          f"{wp['fork_p99_ms']} ms | kills={result['chaos']['kills']} "
          f"recovered={result['chaos']['actors_recovered']}",
          file=sys.stderr)
    if not result["ok"]:
        print("[burst] VIOLATIONS:", file=sys.stderr)
        for v in result["violations"]:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
