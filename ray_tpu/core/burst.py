"""Burst-elasticity chaos harness: scale a synthetic fleet 10 -> 1000
workers under queued load with seeded worker kills — and, in `--nodes`
mode, a multi-raylet NODE kill storm: whole nodes (raylet + its workers +
its fork templates) SIGKILLed together under closed-loop load, with the
autoscaler as the recovery control loop (dead-node reap-and-replace) and
warm node onboarding (hot-env template prewarm) measured end to end.

This is the elasticity story behind "millions of users" made into a
repeatable scenario: a small serving/RL-style fleet of actors is already
busy with a continuous stream of calls when demand arrives and the fleet
must burst to two orders of magnitude more workers — the thing a 4.5 s
cold worker start made a non-starter and the warm worker pool
(`core/worker_pool.py` fork-template zygotes) exists to make routine.
While the fleet scales, a seeded kill loop SIGKILLs random live workers
(fleet actors restart on fresh — warm — workers; the raylet's
recently-completed failover covers results dying in their buffers).

The harness asserts the elasticity contract:

  * every lease is served — each fleet actor ends up alive on a worker
    that was started either by a WARM FORK or a COLD FALLBACK spawn
    (`registered_warm + registered_cold` covers every worker; a lease
    served by neither means the pool invented a worker it can't account
    for, or dropped one);
  * every seeded kill recovers — killed actors come back and answer;
  * the load stream never wedges — every submitted call resolves as a
    result or a typed error within the deadline.

Writes a JSON artifact (burst section of ENVELOPE_r10.json) with
cold-vs-warm start counts, fork latency p50/p99, and
actors-to-first-ping for the scale-up wave. Run directly:

    python -m ray_tpu.core.burst                # full 10 -> 1000 profile
    python -m ray_tpu.core.burst --quick        # 4 -> 40 CI profile
    python -m ray_tpu.core.burst --nodes        # multi-node kill storm
    python -m ray_tpu.core.burst --nodes --quick  # CI node-storm profile

The node storm asserts the NODE failure-domain contract:

  * every seeded node kill is DETECTED — the GCS declares the node dead
    through missed heartbeats alone (no drain notify), within the
    `health_check_period_ms + health_check_timeout_ms` bound;
  * every kill is REPLACED — the autoscaler reaps the corpse at the
    provider and relaunches capacity back to `min_workers`;
  * replacement nodes onboard WARM — the register_node reply's hot env
    keys pre-spawn fork templates, and node-join-to-first-warm-lease is
    tracked as a first-class number (ENVELOPE_r12.json);
  * actors with `max_restarts` land on surviving/replacement nodes and
    every closed-loop call resolves (zero hung).
"""

from __future__ import annotations

import json
import logging
import os
import random
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclass
class BurstProfile:
    n_start: int = 10            # steady-state fleet before the burst
    n_target: int = 1000         # fleet size after the burst
    n_kills: int = 8             # seeded SIGKILLs during the scale-up
    kill_period_s: float = 1.0
    load_inflight: int = 32      # closed-loop in-flight calls on the fleet
    load_warmup_s: float = 2.0   # load runs this long before the burst
    seed: int = 0
    call_timeout_s: float = 120.0
    settle_timeout_s: float = 180.0


QUICK_PROFILE = dict(n_start=4, n_target=40, n_kills=3,
                     kill_period_s=0.5, load_inflight=8,
                     load_warmup_s=1.0, settle_timeout_s=90.0)


class _LoadGen:
    """Closed-loop call stream against the live fleet: keeps
    `inflight` calls outstanding, counts resolutions by outcome. Calls to
    killed actors resolve as typed errors (counted, not fatal) — the one
    forbidden outcome is a call that never resolves."""

    def __init__(self, actors: List, inflight: int, timeout_s: float):
        import ray_tpu

        self._ray = ray_tpu
        self._actors = actors        # shared, grows under the lock
        self._lock = threading.Lock()
        self._inflight = inflight
        self._timeout_s = timeout_s
        self._stop = threading.Event()
        self.completed = 0
        self.errored = 0
        self.hung = 0
        self._threads = [threading.Thread(target=self._run, daemon=True,
                                          name=f"burst-load-{i}")
                         for i in range(min(4, inflight))]

    def add_actors(self, actors: List) -> None:
        with self._lock:
            self._actors.extend(actors)

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def stop(self) -> Dict[str, int]:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self._timeout_s + 10)
            if t.is_alive():
                self.hung += 1
        return {"completed": self.completed, "errored": self.errored,
                "hung": self.hung}

    def _run(self) -> None:
        rng = random.Random(threading.get_ident())
        per_thread = max(1, self._inflight // max(1, len(self._threads)))
        while not self._stop.is_set():
            with self._lock:
                targets = [rng.choice(self._actors)
                           for _ in range(per_thread)]
            refs = [a.work.remote(1) for a in targets]
            for r in refs:
                try:
                    self._ray.get(r, timeout=self._timeout_s)
                    with self._lock:
                        self.completed += 1
                except Exception:
                    # typed resolution (actor died mid-kill, retry budget,
                    # timeout) — the contract only forbids silent hangs,
                    # and a worker killed mid-call surfaces here
                    with self._lock:
                        self.errored += 1


def _pool_stats() -> Dict[str, Any]:
    from ray_tpu.core.worker import current_worker

    return current_worker().raylet.call("worker_pool_stats", {}, timeout=30)


def _list_workers() -> List[Dict[str, Any]]:
    from ray_tpu.core.worker import current_worker

    try:
        return current_worker().raylet.call("list_workers", {}, timeout=30)
    except Exception:
        return []


def _idle_worker_count() -> int:
    return sum(1 for w in _list_workers() if w.get("idle"))


def run_burst(profile: Optional[BurstProfile] = None,
              out_path: Optional[str] = None) -> Dict[str, Any]:
    """Run one burst on the CURRENT cluster (caller already init'd).
    Returns the result dict; the caller asserts on `ok` / `violations`."""
    import ray_tpu

    p = profile or BurstProfile()
    rng = random.Random(p.seed)

    @ray_tpu.remote
    class FleetWorker:
        def __init__(self):
            self._n = 0

        def work(self, x):
            self._n += 1
            return (os.getpid(), self._n)

        def ping(self):
            return os.getpid()

    def make_actors(n: int) -> List:
        return [FleetWorker.options(num_cpus=0, max_restarts=4).remote()
                for _ in range(n)]

    stats0 = _pool_stats()
    # leases may legitimately be served by workers that were ALREADY idle
    # when the burst began (e.g. envelope phases that ran before
    # --elastic): those start nothing and are still warm-pool-served
    idle0 = _idle_worker_count()
    violations: List[str] = []

    # ---- phase 1: steady-state fleet under load -------------------------
    fleet = make_actors(p.n_start)
    pids = ray_tpu.get([a.ping.remote() for a in fleet],
                       timeout=p.settle_timeout_s)
    load = _LoadGen(list(fleet), p.load_inflight, p.call_timeout_s)
    load.start()
    time.sleep(p.load_warmup_s)

    # ---- phase 2: burst to n_target under load + seeded kills -----------
    kills_done = []
    kill_stop = threading.Event()

    def killer():
        # SIGKILL a random live worker every kill_period_s — drawn from a
        # LIVE snapshot so mid-burst forks are fair game too (a recovery
        # bug specific to freshly-forked workers must not hide behind a
        # victim list frozen at burst start). The actor restarts
        # (max_restarts) on a fresh — warm — worker, and results buffered
        # in the dead process fail over via recent_done.
        while len(kills_done) < p.n_kills and not kill_stop.is_set():
            live = [w["pid"] for w in _list_workers()] or list(pids)
            victim = rng.choice(live)
            try:
                os.kill(victim, 9)
                kills_done.append(victim)
            except OSError:
                pass  # raced its own exit; snapshot refreshes next tick
            if kill_stop.wait(p.kill_period_s):
                return

    t0 = time.perf_counter()
    wave = make_actors(p.n_target - p.n_start)
    load.add_actors(wave)
    kt = threading.Thread(target=killer, daemon=True, name="burst-killer")
    kt.start()
    # first-ping with kill-recovery: the killer may SIGKILL a wave actor
    # mid-ping (typed error); the restarted actor is re-pinged until the
    # settle budget runs out — only an actor that NEVER answers violates
    wave_pids = []
    deadline = t0 + p.settle_timeout_s
    pending = [(a, a.ping.remote()) for a in wave]
    while pending and time.perf_counter() < deadline:
        retry = []
        for a, r in pending:
            try:
                wave_pids.append(ray_tpu.get(
                    r, timeout=max(0.5, deadline - time.perf_counter())))
            except Exception:
                retry.append((a, a.ping.remote()))
        pending = retry
        if pending:
            time.sleep(0.2)
    if pending:
        violations.append(
            f"{len(pending)} scale-up actors never answered first ping")
    t_wave = time.perf_counter() - t0
    # a fast scale-up must not let the chaos off the hook: the killer
    # finishes its seeded budget (bounded) before recovery is judged
    kt.join(timeout=p.n_kills * p.kill_period_s + 10)
    kill_stop.set()
    kt.join(timeout=10)

    # ---- phase 3: settle — every actor (incl. killed ones) answers ------
    recovered = 0
    t_settle0 = time.perf_counter()
    deadline = t_settle0 + p.settle_timeout_s
    for a in fleet + list(wave):
        try:
            ray_tpu.get(a.ping.remote(),
                        timeout=max(1.0, deadline - time.perf_counter()))
            recovered += 1
        except Exception as e:
            violations.append(f"actor never recovered: {type(e).__name__}")
    load_counts = load.stop()
    if load_counts["hung"]:
        violations.append(f"{load_counts['hung']} load calls never resolved")

    stats1 = _pool_stats()
    warm = stats1["registered_warm"] - stats0["registered_warm"]
    cold = stats1["registered_cold"] - stats0["registered_cold"]
    total_actors = p.n_target
    # every lease must be served by a warm fork, a cold fallback, or a
    # worker that was already idle at burst start; kills and restarts only
    # ADD workers on top of the fleet itself
    if warm + cold + idle0 < recovered:
        violations.append(
            f"workers unaccounted for: {recovered} live actors but only "
            f"{warm} warm + {cold} cold starts recorded "
            f"(+{idle0} pre-burst idle)")
    if recovered < total_actors:
        violations.append(
            f"only {recovered}/{total_actors} leases ended up served")

    result = {
        "suite": "burst-elasticity (warm worker pool chaos)",
        "profile": {
            "n_start": p.n_start, "n_target": p.n_target,
            "n_kills": p.n_kills, "seed": p.seed,
            "load_inflight": p.load_inflight,
        },
        "scale_up": {
            "actors_to_first_ping_s": round(t_wave, 2),
            "actors_per_s": round((p.n_target - p.n_start) / t_wave, 1),
            "distinct_workers": len(set(wave_pids)),
        },
        "worker_pool": {
            "warm_starts": warm, "cold_starts": cold,
            "pre_burst_idle_workers": idle0,
            "warm_fraction": round(warm / max(1, warm + cold), 3),
            "fork_p50_ms": stats1["fork_p50_ms"],
            "fork_p99_ms": stats1["fork_p99_ms"],
            "template_respawns": stats1["template_respawns"]
            - stats0["template_respawns"],
        },
        "chaos": {
            "kills": len(kills_done),
            "actors_recovered": recovered,
        },
        "load": load_counts,
        "violations": violations,
        "ok": not violations,
    }
    for a in fleet + list(wave):
        try:
            ray_tpu.kill(a)
        except Exception:
            pass
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    return result


# --------------------------------------------------------------------------
# node kill storm (multi-raylet, autoscaler-driven recovery)


@dataclass
class NodeStormProfile:
    n_nodes: int = 4             # fleet nodes the autoscaler maintains
    node_cpus: float = 2.0
    actors_per_node: int = 4     # fleet capacity == actors: survivors stay
    #                              FULL, so restarts MUST land on replacements
    n_node_kills: int = 3        # seeded whole-node SIGKILLs
    kill_period_s: float = 5.0
    load_inflight: int = 16
    load_warmup_s: float = 2.0
    seed: int = 0
    call_timeout_s: float = 60.0
    settle_timeout_s: float = 120.0
    detect_timeout_s: float = 30.0
    # fast-detection knobs patched into the shared config for the run
    health_check_period_ms: int = 500
    health_check_timeout_ms: int = 3000


NODE_QUICK_PROFILE = dict(n_nodes=3, actors_per_node=3, n_node_kills=2,
                          kill_period_s=4.0, load_inflight=8,
                          load_warmup_s=1.0, settle_timeout_s=90.0)


def run_node_storm(profile: Optional[NodeStormProfile] = None,
                   out_path: Optional[str] = None) -> Dict[str, Any]:
    """One node kill storm on a fresh in-process multi-raylet cluster.
    Boots its own Cluster + FakeNodeProvider + StandardAutoscaler; the
    caller must NOT have ray_tpu initialized."""
    import ray_tpu
    from ray_tpu.autoscaler import FakeNodeProvider, NodeType, \
        StandardAutoscaler
    from ray_tpu.core.cluster import Cluster
    from ray_tpu.core.config import get_config

    p = profile or NodeStormProfile()
    rng = random.Random(p.seed)
    cfg = get_config()
    saved = (cfg.health_check_period_ms, cfg.health_check_timeout_ms)
    cfg.health_check_period_ms = p.health_check_period_ms
    cfg.health_check_timeout_ms = p.health_check_timeout_ms
    detection_bound_s = (p.health_check_period_ms
                         + p.health_check_timeout_ms) / 1000.0

    violations: List[str] = []
    removed_events: Dict[str, float] = {}   # node hexid -> t_removed
    events_lock = threading.Lock()

    def on_nodes_event(msg):
        if msg.get("event") == "removed":
            with events_lock:
                removed_events.setdefault(msg["node_id"].hex(),
                                          time.monotonic())

    # boot INSIDE the try: a failed boot must still restore the patched
    # health-check config and tear down whatever came up
    cluster = None
    provider = None
    autoscaler = None
    load: Optional[_LoadGen] = None
    try:
        cluster = Cluster()
        cluster.add_node(num_cpus=4, resources={"head": 1})
        cluster.connect()
        provider = FakeNodeProvider(cluster.gcs_address)
        fleet_cap = float(p.actors_per_node)
        autoscaler = StandardAutoscaler(
            cluster.gcs_address, provider,
            [NodeType("storm", {"CPU": p.node_cpus, "fleet": fleet_cap},
                      min_workers=p.n_nodes,
                      max_workers=p.n_nodes + p.n_node_kills + 2)],
            update_interval_s=0.25, idle_timeout_s=10_000.0)
        from ray_tpu.core.worker import current_worker

        driver = current_worker()
        driver.subscribe_channel("nodes", on_nodes_event)
        autoscaler.start()

        # ---- phase 1: the fleet forms -----------------------------------
        deadline = time.monotonic() + p.settle_timeout_s

        def alive_fleet_nodes() -> List[dict]:
            nodes = driver.gcs.call("get_all_nodes", {}, timeout=10)
            return [n for n in nodes if n.get("alive")
                    and "fleet" in n.get("resources_total", {})]

        while len(alive_fleet_nodes()) < p.n_nodes:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"fleet never formed: {len(alive_fleet_nodes())}"
                    f"/{p.n_nodes} nodes")
            time.sleep(0.2)
        initial_pids = set(provider.non_terminated_nodes())

        @ray_tpu.remote
        class FleetWorker:
            def __init__(self):
                self._n = 0

            def work(self, x):
                self._n += 1
                return (os.getpid(), self._n)

            def ping(self):
                return os.getpid()

        n_actors = p.n_nodes * p.actors_per_node
        fleet = [FleetWorker.options(num_cpus=0, max_restarts=8,
                                     resources={"fleet": 1.0}).remote()
                 for _ in range(n_actors)]
        ray_tpu.get([a.ping.remote() for a in fleet],
                    timeout=p.settle_timeout_s)
        load = _LoadGen(list(fleet), p.load_inflight, p.call_timeout_s)
        load.start()
        time.sleep(p.load_warmup_s)

        # ---- phase 2: seeded whole-node kills under load ----------------
        kills: List[Dict[str, Any]] = []
        killed_pids: set = set()
        for _ in range(p.n_node_kills):
            # a LIVE victim drawn from the provider view (replacements are
            # fair game once they joined), mapped to its cluster node id
            # BEFORE the kill so detection can be attributed. Excludes
            # nodes WE killed, not just detected ones: a corpse stays
            # provider-listed until the autoscaler reaps it, and drawing
            # it twice would record two kills for one node.
            candidates = []
            with events_lock:
                seen_dead = set(removed_events)
            for pid in provider.non_terminated_nodes():
                raylet = provider.raylet_for(pid)
                if raylet is not None and pid not in killed_pids \
                        and raylet.node_id.hex() not in seen_dead:
                    candidates.append((pid, raylet.node_id.hex()))
            if not candidates:
                violations.append("no live node left to kill")
                break
            pid, hexid = rng.choice(candidates)
            logger.warning("node storm: SIGKILLing node %s (%s)",
                           pid, hexid[:8])
            t_kill = time.monotonic()
            provider.kill_node(pid)
            killed_pids.add(pid)
            kills.append({"pid": pid, "node": hexid, "t_kill": t_kill})
            time.sleep(p.kill_period_s)

        # ---- phase 3: every kill detected, every node replaced ----------
        detect_deadline = time.monotonic() + p.detect_timeout_s
        for k in kills:
            while True:
                with events_lock:
                    t_removed = removed_events.get(k["node"])
                if t_removed is not None:
                    k["detect_s"] = round(t_removed - k["t_kill"], 3)
                    break
                if time.monotonic() > detect_deadline:
                    violations.append(
                        f"node kill {k['node'][:8]} never detected")
                    break
                time.sleep(0.1)
        detect_lat = sorted(k["detect_s"] for k in kills
                            if "detect_s" in k)
        for k in kills:
            if "detect_s" in k and k["detect_s"] > detection_bound_s * 1.5:
                violations.append(
                    f"detection of {k['node'][:8]} took {k['detect_s']}s "
                    f"(> 1.5x the {detection_bound_s}s health bound)")

        replace_deadline = time.monotonic() + p.settle_timeout_s
        while len(alive_fleet_nodes()) < p.n_nodes:
            if time.monotonic() > replace_deadline:
                violations.append(
                    f"fleet never healed: {len(alive_fleet_nodes())}"
                    f"/{p.n_nodes} alive nodes after the storm")
                break
            time.sleep(0.2)

        # ---- phase 4: settle — every actor answers, placement is live ---
        recovered = 0
        settle_deadline = time.monotonic() + p.settle_timeout_s
        last_err: Dict[int, str] = {}
        if os.environ.get("RAY_TPU_NODE_STORM_DUMP_STACKS"):
            # watchdog: if the settle phase wedges (a ping .remote() or
            # get() blocking past its budget), dump every thread so the
            # stuck frame is named instead of inferred
            import faulthandler

            faulthandler.dump_traceback_later(
                p.settle_timeout_s * 0.8, exit=False, file=sys.stderr)
        pending = [(a, a.ping.remote()) for a in fleet]
        while pending and time.monotonic() < settle_deadline:
            retry = []
            for a, r in pending:
                # per-get budget bounded: one wedged ref must not burn the
                # whole settle budget serially and mask the others
                per_get = min(10.0, max(
                    0.5, settle_deadline - time.monotonic()))
                try:
                    ray_tpu.get(r, timeout=per_get)
                    recovered += 1
                except Exception as e:
                    last_err[id(a)] = f"{type(e).__name__}: {e}"[:160]
                    retry.append((a, a.ping.remote()))
            pending = retry
            if pending:
                time.sleep(0.3)
        if pending:
            # "?" = no get() error was ever recorded, i.e. the ping
            # .remote() itself blocked out the settle budget (an actor
            # stuck RESTARTING blocks submission in _wait_actor_address) —
            # pull the GCS state so the failure names the stuck actor
            errs: Dict[str, int] = {}
            for a, _ in pending:
                key = last_err.get(id(a), "?")
                if key == "?":
                    try:
                        info = driver.get_actor_info(actor_id=a._actor_id)
                        key = (f"no get error; GCS state="
                               f"{info.get('state') if info else None}")
                    except Exception:
                        pass
                errs[key] = errs.get(key, 0) + 1
            violations.append(
                f"{len(pending)} actors never recovered from node kills "
                f"(last errors: {errs})")
            if os.environ.get("RAY_TPU_NODE_STORM_DUMP_STACKS"):
                import faulthandler

                faulthandler.dump_traceback(file=sys.stderr)
        if os.environ.get("RAY_TPU_NODE_STORM_DUMP_STACKS"):
            import faulthandler

            faulthandler.cancel_dump_traceback_later()
        load_counts = load.stop()
        load = None  # stopped; the finally must not re-join it
        if load_counts["hung"]:
            violations.append(
                f"{load_counts['hung']} load calls never resolved")

        # placement: every actor sits on an ALIVE node; count how many
        # landed on replacement (post-storm) nodes
        alive_ids = {n["node_id"] for n in
                     driver.gcs.call("get_all_nodes", {}, timeout=10)
                     if n.get("alive")}
        on_replacements = 0
        replacement_pids = [pid for pid in provider.non_terminated_nodes()
                            if pid not in initial_pids]
        replacement_ids = {provider.raylet_for(pid).node_id.binary()
                           for pid in replacement_pids
                           if provider.raylet_for(pid) is not None}
        for a in fleet:
            info = driver.get_actor_info(actor_id=a._actor_id)
            if not info or info.get("state") != "ALIVE":
                continue
            nid = info.get("node_id")
            if nid is not None and nid not in alive_ids:
                violations.append(
                    f"actor {info['actor_id']} reports a DEAD node")
            if nid in replacement_ids:
                on_replacements += 1
        if kills and not on_replacements:
            violations.append("no restarted actor landed on a replacement "
                              "node (survivors were full — placement is "
                              "wrong)")

        # ---- warm onboarding numbers ------------------------------------
        warm_joins = []
        for pid in replacement_pids:
            raylet = provider.raylet_for(pid)
            if raylet is None:
                continue
            s = raylet._worker_pool.stats()
            if s.get("join_to_first_warm_lease_s") is not None:
                warm_joins.append(s["join_to_first_warm_lease_s"])
        if replacement_pids and not warm_joins:
            violations.append("no replacement node served a warm (forked) "
                              "lease — onboarding prewarm is not working")

        gcs_node_stats = driver.gcs.call("gcs_stats", {}, timeout=10) \
            .get("node_failure", {})
        auto_stats = autoscaler.stats()
        if auto_stats["relaunches"] < len(kills):
            violations.append(
                f"autoscaler relaunched {auto_stats['relaunches']} "
                f"< {len(kills)} kills")

        result = {
            "suite": "node-kill-storm (autoscaler node failure domain)",
            "profile": {
                "n_nodes": p.n_nodes, "actors_per_node": p.actors_per_node,
                "n_node_kills": p.n_node_kills, "seed": p.seed,
                "load_inflight": p.load_inflight,
                "health_check_period_ms": p.health_check_period_ms,
                "health_check_timeout_ms": p.health_check_timeout_ms,
            },
            "chaos": {
                "node_kills": len(kills),
                "detected": len(detect_lat),
                "detection_bound_s": detection_bound_s,
                "node_death_detection_s": {
                    "p50": detect_lat[len(detect_lat) // 2]
                    if detect_lat else None,
                    "max": detect_lat[-1] if detect_lat else None,
                },
                "kills": [{"node": k["node"][:8],
                           "detect_s": k.get("detect_s")} for k in kills],
            },
            "onboarding": {
                "node_join_to_first_warm_lease_s":
                    sorted(warm_joins)[len(warm_joins) // 2]
                    if warm_joins else None,
                "per_replacement": warm_joins,
                "replacements": len(replacement_pids),
            },
            "actors": {
                "total": n_actors,
                "recovered": recovered,
                "on_replacement_nodes": on_replacements,
            },
            "autoscaler": auto_stats,
            "gcs_node_failure": gcs_node_stats,
            "load": load_counts,
            "violations": violations,
            "ok": not violations,
        }
        for a in fleet:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        if out_path:
            with open(out_path, "w") as f:
                json.dump(result, f, indent=2)
                f.write("\n")
        return result
    finally:
        if load is not None:
            # an exception escaped mid-storm: silence the load threads
            # BEFORE tearing the cluster down under them
            try:
                load.stop()
            except Exception:
                pass
        if autoscaler is not None:
            try:
                autoscaler.stop()
            except Exception:
                pass
        if provider is not None:
            for pid in provider.non_terminated_nodes():
                try:
                    provider.terminate_node(pid)
                except Exception:
                    pass
        if cluster is not None:
            try:
                cluster.shutdown()
            except Exception:
                logger.exception("node storm cluster shutdown failed")
        cfg.health_check_period_ms, cfg.health_check_timeout_ms = saved


def _node_storm_main(args) -> int:
    kw: Dict[str, Any] = dict(NODE_QUICK_PROFILE) if args.quick else {}
    kw["seed"] = args.seed
    if args.kills is not None:
        kw["n_node_kills"] = args.kills
    p = NodeStormProfile(**kw)
    result = run_node_storm(p, out_path=args.json)
    print(json.dumps(result, indent=2))
    c, o = result["chaos"], result["onboarding"]
    print(f"[node-storm] seed={p.seed} nodes={p.n_nodes} "
          f"kills={c['node_kills']} detected={c['detected']} "
          f"(p50 {c['node_death_detection_s']['p50']}s, bound "
          f"{c['detection_bound_s']}s) | replacements={o['replacements']} "
          f"join->first-warm-lease={o['node_join_to_first_warm_lease_s']}s "
          f"| actors recovered={result['actors']['recovered']}"
          f"/{result['actors']['total']} "
          f"(on replacements: {result['actors']['on_replacement_nodes']}) "
          f"| load={result['load']}", file=sys.stderr)
    if not result["ok"]:
        print("[node-storm] VIOLATIONS:", file=sys.stderr)
        for v in result["violations"]:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="scaled-down CI profile (4 -> 40 workers)")
    ap.add_argument("--nodes", action="store_true",
                    help="multi-raylet NODE kill storm (autoscaler-driven "
                         "replacement + warm onboarding)")
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get(
                        "RAY_TPU_FAULT_INJECTION_SEED", "0")))
    ap.add_argument("--start", type=int, default=None)
    ap.add_argument("--target", type=int, default=None)
    ap.add_argument("--kills", type=int, default=None)
    ap.add_argument("--json", default=None, help="write the result here")
    args = ap.parse_args(argv)

    if args.nodes:
        return _node_storm_main(args)

    kw: Dict[str, Any] = dict(QUICK_PROFILE) if args.quick else {}
    kw["seed"] = args.seed
    if args.start is not None:
        kw["n_start"] = args.start
    if args.target is not None:
        kw["n_target"] = args.target
    if args.kills is not None:
        kw["n_kills"] = args.kills
    p = BurstProfile(**kw)

    import ray_tpu

    # enough CPU headroom that the fleet (num_cpus=0 actors) and the load
    # stream never contend on scheduler admission
    ray_tpu.init(num_cpus=8)
    try:
        result = run_burst(p, out_path=args.json)
    finally:
        ray_tpu.shutdown()

    print(json.dumps(result, indent=2))
    wp, su = result["worker_pool"], result["scale_up"]
    print(f"[burst] seed={p.seed} {p.n_start} -> {p.n_target} workers in "
          f"{su['actors_to_first_ping_s']}s | warm={wp['warm_starts']} "
          f"cold={wp['cold_starts']} (warm fraction "
          f"{wp['warm_fraction']}) fork p50/p99 = {wp['fork_p50_ms']}/"
          f"{wp['fork_p99_ms']} ms | kills={result['chaos']['kills']} "
          f"recovered={result['chaos']['actors_recovered']}",
          file=sys.stderr)
    if not result["ok"]:
        print("[burst] VIOLATIONS:", file=sys.stderr)
        for v in result["violations"]:
            print(f"  - {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
