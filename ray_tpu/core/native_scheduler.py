"""ctypes binding for the native cluster scheduler
(src/scheduler/cluster_scheduler.cpp).

Mirrors the reference's C++ scheduling stack
(src/ray/raylet/scheduling/cluster_resource_scheduler.cc,
policy/hybrid_scheduling_policy.cc, policy/bundle_scheduling_policy.cc):
the hot select/place decisions run in native code with fixed-point
resource math; `ray_tpu.core.scheduler.SchedulingPolicy` delegates here
when the library is available and falls back to the pure-Python spec
otherwise.

Built on demand with g++ (cached by source hash under build/), same
pattern as core/arena.py.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "src", "scheduler", "cluster_scheduler.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")

_lib = None
_lib_lock = threading.Lock()
_lib_failed = False

_OUT_CAP = 1 << 16


def _load_lib():
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            with open(_SRC, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()[:16]
            so_path = os.path.join(_BUILD_DIR, f"libsched-{digest}.so")
            if not os.path.exists(so_path):
                os.makedirs(_BUILD_DIR, exist_ok=True)
                tmp = so_path + f".tmp{os.getpid()}"
                subprocess.run(
                    ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                     "-o", tmp, _SRC],
                    check=True, capture_output=True)
                os.replace(tmp, so_path)
            lib = ctypes.CDLL(so_path)
            lib.sched_create.restype = ctypes.c_void_p
            lib.sched_create.argtypes = [ctypes.c_double]
            lib.sched_destroy.argtypes = [ctypes.c_void_p]
            lib.sched_clear.argtypes = [ctypes.c_void_p]
            lib.sched_set_threshold.argtypes = [
                ctypes.c_void_p, ctypes.c_double]
            lib.sched_upsert_node.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_char_p, ctypes.c_char_p]
            lib.sched_remove_node.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.sched_select.restype = ctypes.c_int
            lib.sched_select.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
            lib.sched_place_bundles.restype = ctypes.c_int
            lib.sched_place_bundles.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_char_p, ctypes.c_int]
            lib.sched_num_nodes.restype = ctypes.c_int
            lib.sched_num_nodes.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception:
            _lib_failed = True
            _lib = None
        return _lib


def available() -> bool:
    return _load_lib() is not None


def _fmt_resources(res: Dict[str, float]) -> bytes:
    return ";".join(f"{k}={float(v)!r}" for k, v in res.items()).encode()


def _fmt_labels(labels: Dict[str, str]) -> bytes:
    return ";".join(f"{k}={v}" for k, v in (labels or {}).items()).encode()


class NativeScheduler:
    """Owns one native scheduler instance; callers sync node views then
    ask for select/place decisions."""

    def __init__(self, spread_threshold: float = 0.5):
        lib = _load_lib()
        if lib is None:
            raise RuntimeError("native scheduler library unavailable")
        self._lib = lib
        self._handle = lib.sched_create(ctypes.c_double(spread_threshold))
        self._out = ctypes.create_string_buffer(_OUT_CAP)
        self._threshold = spread_threshold
        # last-synced wire view per node id; sync_nodes diffs against this so
        # steady-state decisions only re-parse nodes whose view changed
        self._view: Dict[bytes, tuple] = {}

    def __del__(self):
        try:
            if getattr(self, "_handle", None):
                self._lib.sched_destroy(self._handle)
                self._handle = None
        except Exception:
            pass

    def set_spread_threshold(self, threshold: float) -> None:
        if threshold != self._threshold:
            self._lib.sched_set_threshold(
                self._handle, ctypes.c_double(threshold))
            self._threshold = threshold

    def sync_nodes(self, nodes) -> None:
        """Replace the full node view (list of core.scheduler.NodeView),
        upserting only nodes whose serialized view changed since the last
        sync and removing vanished ones."""
        seen = {}
        for n in nodes:
            wire = (_fmt_resources(n.total), _fmt_resources(n.available),
                    _fmt_labels(getattr(n, "labels", None)))
            seen[n.node_id] = wire
            if self._view.get(n.node_id) != wire:
                self._lib.sched_upsert_node(
                    self._handle, n.node_id.hex().encode(), *wire)
        for node_id in list(self._view):
            if node_id not in seen:
                self._lib.sched_remove_node(self._handle,
                                            node_id.hex().encode())
        self._view = seen

    def upsert_node(self, node_id: bytes, total: Dict[str, float],
                    available_res: Dict[str, float],
                    labels: Optional[Dict[str, str]] = None) -> None:
        self._lib.sched_upsert_node(
            self._handle, node_id.hex().encode(), _fmt_resources(total),
            _fmt_resources(available_res), _fmt_labels(labels or {}))

    def remove_node(self, node_id: bytes) -> None:
        self._lib.sched_remove_node(self._handle, node_id.hex().encode())

    def select(self, demand: Dict[str, float], strategy: str = "HYBRID",
               prefer_node: Optional[bytes] = None) -> Optional[bytes]:
        n = self._lib.sched_select(
            self._handle, _fmt_resources(demand), strategy.encode(),
            (prefer_node.hex() if prefer_node else "").encode(),
            self._out, _OUT_CAP)
        if n < 0:
            raise RuntimeError("native scheduler output buffer overflow")
        if n == 0:
            return None
        return bytes.fromhex(self._out.value.decode())

    def place_bundles(self, bundles: List[Dict[str, float]],
                      strategy: str) -> Optional[List[bytes]]:
        wire = "|".join(
            ";".join(f"{k}={float(v)!r}" for k, v in b.items())
            for b in bundles).encode()
        n = self._lib.sched_place_bundles(
            self._handle, wire, strategy.encode(), self._out, _OUT_CAP)
        if n < 0:
            raise RuntimeError("native scheduler output buffer overflow")
        if n == 0:
            return None
        return [bytes.fromhex(p) for p in self._out.value.decode().split(";")]
