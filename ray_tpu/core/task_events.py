"""Worker-side batched task-event + profile-span shipping.

Equivalent of the reference's TaskEventBuffer
(`src/ray/core_worker/task_event_buffer.h`): task lifecycle transitions
(SUBMITTED/RUNNING/FINISHED/FAILED) and chrome-trace spans coalesce in the
emitting process and flush to the GCS as ONE `task_events_batch` notify per
`task_events_report_interval_ms` (and at shutdown), instead of one RPC per
transition plus a profile flush after every execution. A driver submitting
N tasks therefore issues O(elapsed/interval) control-plane RPCs, not O(N).

The buffer is bounded (`task_events_max_buffer_size`): overflow drops the
OLDEST events and counts them, and the dropped count rides the next flush so
the GCS-side truncation counter stays honest (mirroring the eviction
counter the GCS ring already keeps, gcs.py)."""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Optional

from ray_tpu.core.config import get_config

logger = logging.getLogger(__name__)


class TaskEventBuffer:
    def __init__(self, worker):
        self._worker = worker
        self._lock = threading.Lock()
        self._events: deque = deque()
        self._dropped = 0
        # drain cursor into the tracing ring (sequence number, not a list
        # index — survives ring overflow between flushes)
        self._profile_sent = 0
        # spans the tracing ring dropped but whose count failed delivery —
        # re-shipped with the next flush so truncation stays honest
        self._spans_dropped_pending = 0
        # NTP-style clock offset vs the GCS (tracing_enabled only):
        # offset_us = t1 - (t0 + t2) / 2 from one clock_probe round-trip,
        # re-estimated every tracing_clock_probe_period_s and shipped with
        # each flush for merge-time cross-node alignment
        self._clock_offset_us: Optional[float] = None
        self._clock_probe_at = 0.0
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._stopped = False
        self.flush_count = 0  # instrumentation for tests

    def record(self, spec, state: str) -> None:
        """Buffer one task-state transition (same payload the per-event
        notify used to carry). Starts the flush timer lazily so processes
        that never emit events never spawn the thread."""
        w = self._worker
        ev = {
            "task_id": spec.task_id.binary(),
            "name": spec.method_name,
            "type": spec.task_type.name,
            "state": state,
            "job_id": spec.job_id.binary(),
            "node_id": w.node_id,
            "worker_id": w.worker_id.binary(),
        }
        start = None
        with self._lock:
            self._events.append(ev)
            limit = max(1, get_config().task_events_max_buffer_size)
            while len(self._events) > limit:
                self._events.popleft()
                self._dropped += 1
            if self._thread is None and not self._stopped:
                start = threading.Thread(target=self._loop,
                                         name="task-events", daemon=True)
                self._thread = start
        if start is not None:
            start.start()

    def _loop(self) -> None:
        while not self._stopped and not self._worker._shutdown.is_set():
            self._wake.wait(get_config().task_events_report_interval_ms / 1000.0)
            self._wake.clear()
            try:
                self.flush()
            except Exception:
                logger.debug("task event flush failed", exc_info=True)

    def _probe_clock(self) -> None:
        """One clock_probe round-trip against the GCS: the midpoint of the
        local send/recv stamps estimates when t1 was read remotely, so
        offset = t1 - (t0 + t2) / 2 (classic NTP). Best-effort — a down
        link just leaves the previous estimate in place."""
        import time as _time

        try:
            t0 = _time.time() * 1e6
            reply = self._worker.gcs.call("clock_probe", timeout=2)
            t2 = _time.time() * 1e6
            self._clock_offset_us = reply["t1_us"] - (t0 + t2) / 2.0
        except Exception:
            logger.debug("clock probe failed", exc_info=True)

    def flush(self) -> None:
        """Ship everything buffered (task events, dropped count, and any
        tracing spans recorded since the last flush) in one GCS notify."""
        import time as _time

        from ray_tpu.core.config import get_config as _get_config
        from ray_tpu.util import tracing

        with self._lock:
            events = list(self._events)
            self._events.clear()
            dropped, self._dropped = self._dropped, 0
            fresh, self._profile_sent, spans_dropped = tracing.drain(
                self._profile_sent)
            spans_dropped += self._spans_dropped_pending
            self._spans_dropped_pending = 0
        if not events and not fresh and not dropped and not spans_dropped:
            return
        src = self._worker.worker_id.binary().hex()
        payload = {
            "events": events,
            "dropped": dropped,
            "src": src,
            "spans_dropped": spans_dropped,
            "profile_events": [{**e, "_src": src} for e in fresh],
        }
        if tracing.enabled():
            now = _time.monotonic()
            if (self._clock_offset_us is None or now >= self._clock_probe_at):
                self._clock_probe_at = now + max(
                    1.0, _get_config().tracing_clock_probe_period_s)
                self._probe_clock()
            if self._clock_offset_us is not None:
                payload["clock_offset_us"] = self._clock_offset_us
        # try_notify reports a down link (plain notify swallows it); fakes
        # and raw clients in tests surface failure by raising instead
        gcs = self._worker.gcs
        sender = getattr(gcs, "try_notify", None)
        try:
            delivered = (sender("task_events_batch", payload)
                         if sender is not None
                         else (gcs.notify("task_events_batch", payload), True)[1])
        except Exception:
            delivered = False
        if delivered:
            self.flush_count += 1
            return
        # Task events go back for the next tick (a GCS-restart window must
        # not silently lose lifecycle history); spans are best-effort, as
        # they were under per-execution flushing — but their DROP COUNT is
        # not (it's the only record those spans existed), so it re-rides.
        with self._lock:
            self._events.extendleft(reversed(events))
            self._dropped += dropped
            self._spans_dropped_pending += spans_dropped
            limit = max(1, get_config().task_events_max_buffer_size)
            while len(self._events) > limit:
                self._events.popleft()
                self._dropped += 1
        logger.debug("task event batch notify not delivered (GCS link down)")

    def stop(self) -> None:
        """Final flush at shutdown (the at-exit half of the batching
        contract: nothing buffered may be lost to a clean exit)."""
        self._stopped = True
        self._wake.set()
        try:
            self.flush()
        except Exception:
            logger.debug("final task event flush failed", exc_info=True)
