"""Worker-side batched task-event + profile-span shipping.

Equivalent of the reference's TaskEventBuffer
(`src/ray/core_worker/task_event_buffer.h`): task lifecycle transitions
(SUBMITTED/RUNNING/FINISHED/FAILED) and chrome-trace spans coalesce in the
emitting process and flush to the GCS as ONE `task_events_batch` notify per
`task_events_report_interval_ms` (and at shutdown), instead of one RPC per
transition plus a profile flush after every execution. A driver submitting
N tasks therefore issues O(elapsed/interval) control-plane RPCs, not O(N).

The buffer is bounded (`task_events_max_buffer_size`): overflow drops the
OLDEST events and counts them, and the dropped count rides the next flush so
the GCS-side truncation counter stays honest (mirroring the eviction
counter the GCS ring already keeps, gcs.py)."""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Optional

from ray_tpu.core.config import get_config

logger = logging.getLogger(__name__)


class TaskEventBuffer:
    def __init__(self, worker):
        self._worker = worker
        self._lock = threading.Lock()
        self._events: deque = deque()
        self._dropped = 0
        # cursor into tracing.get_events() — spans before it were shipped
        self._profile_sent = 0
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._stopped = False
        self.flush_count = 0  # instrumentation for tests

    def record(self, spec, state: str) -> None:
        """Buffer one task-state transition (same payload the per-event
        notify used to carry). Starts the flush timer lazily so processes
        that never emit events never spawn the thread."""
        w = self._worker
        ev = {
            "task_id": spec.task_id.binary(),
            "name": spec.method_name,
            "type": spec.task_type.name,
            "state": state,
            "job_id": spec.job_id.binary(),
            "node_id": w.node_id,
            "worker_id": w.worker_id.binary(),
        }
        start = None
        with self._lock:
            self._events.append(ev)
            limit = max(1, get_config().task_events_max_buffer_size)
            while len(self._events) > limit:
                self._events.popleft()
                self._dropped += 1
            if self._thread is None and not self._stopped:
                start = threading.Thread(target=self._loop,
                                         name="task-events", daemon=True)
                self._thread = start
        if start is not None:
            start.start()

    def _loop(self) -> None:
        while not self._stopped and not self._worker._shutdown.is_set():
            self._wake.wait(get_config().task_events_report_interval_ms / 1000.0)
            self._wake.clear()
            try:
                self.flush()
            except Exception:
                logger.debug("task event flush failed", exc_info=True)

    def flush(self) -> None:
        """Ship everything buffered (task events, dropped count, and any
        tracing spans recorded since the last flush) in one GCS notify."""
        from ray_tpu.util import tracing

        with self._lock:
            events = list(self._events)
            self._events.clear()
            dropped, self._dropped = self._dropped, 0
            spans = tracing.get_events()
            if self._profile_sent > len(spans):
                self._profile_sent = 0  # tracing.clear() ran; resync
            fresh = spans[self._profile_sent:]
            self._profile_sent = len(spans)
        if not events and not fresh and not dropped:
            return
        src = self._worker.worker_id.binary().hex()
        payload = {
            "events": events,
            "dropped": dropped,
            "profile_events": [{**e, "_src": src} for e in fresh],
        }
        # try_notify reports a down link (plain notify swallows it); fakes
        # and raw clients in tests surface failure by raising instead
        gcs = self._worker.gcs
        sender = getattr(gcs, "try_notify", None)
        try:
            delivered = (sender("task_events_batch", payload)
                         if sender is not None
                         else (gcs.notify("task_events_batch", payload), True)[1])
        except Exception:
            delivered = False
        if delivered:
            self.flush_count += 1
            return
        # Task events go back for the next tick (a GCS-restart window must
        # not silently lose lifecycle history); spans are best-effort, as
        # they were under per-execution flushing.
        with self._lock:
            self._events.extendleft(reversed(events))
            self._dropped += dropped
            limit = max(1, get_config().task_events_max_buffer_size)
            while len(self._events) > limit:
                self._events.popleft()
                self._dropped += 1
        logger.debug("task event batch notify not delivered (GCS link down)")

    def stop(self) -> None:
        """Final flush at shutdown (the at-exit half of the batching
        contract: nothing buffered may be lost to a clean exit)."""
        self._stopped = True
        self._wake.set()
        try:
            self.flush()
        except Exception:
            logger.debug("final task event flush failed", exc_info=True)
