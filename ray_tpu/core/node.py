"""Node assembly: boot GCS + raylet for a head node.

Equivalent of the reference's `python/ray/_private/node.py` process
supervisor (`start_head_processes:1139`), redesigned: GCS and raylet are
asyncio servers on threads inside one process rather than separate C++
binaries — worker processes are still real subprocesses. `Cluster`
(cluster.py) adds more raylets for multi-node semantics.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from ray_tpu.core.gcs import GcsServer
from ray_tpu.core.raylet import Raylet


def default_node_resources(num_cpus: Optional[int] = None,
                           resources: Optional[Dict[str, float]] = None) -> Dict[str, float]:
    r = dict(resources or {})
    r.setdefault("CPU", float(num_cpus if num_cpus is not None else (os.cpu_count() or 1)))
    return r


def detect_tpu_labels() -> Dict[str, str]:
    """Detect local TPU topology labels, if any (best-effort, no jax import)."""
    labels: Dict[str, str] = {}
    if os.environ.get("TPU_WORKER_ID") is not None:
        labels["tpu_worker_id"] = os.environ["TPU_WORKER_ID"]
    if os.environ.get("TPU_ACCELERATOR_TYPE"):
        labels["tpu_accelerator_type"] = os.environ["TPU_ACCELERATOR_TYPE"]
    return labels


class HeadNode:
    def __init__(
        self,
        num_cpus: Optional[int] = None,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        object_store_memory: Optional[int] = None,
    ):
        self._gcs = GcsServer()
        self._resources = default_node_resources(num_cpus, resources)
        self._labels = {**detect_tpu_labels(), **(labels or {})}
        self._object_store_memory = object_store_memory
        self._raylet: Optional[Raylet] = None

    def start(self) -> None:
        self._gcs.start()
        self._raylet = Raylet(
            gcs_address=self._gcs.address,
            resources=dict(self._resources),
            labels=self._labels,
            object_store_memory=self._object_store_memory,
        )
        self._raylet.start()

    @property
    def gcs_address(self) -> str:
        return self._gcs.address

    @property
    def raylet_address(self) -> str:
        return self._raylet.address

    @property
    def gcs(self) -> GcsServer:
        return self._gcs

    @property
    def raylet(self) -> Raylet:
        return self._raylet

    def stop(self) -> None:
        if self._raylet is not None:
            self._raylet.stop()
        self._gcs.stop()
