"""Task/actor specifications that travel on the wire.

Equivalent of the reference's `TaskSpecification`
(`src/ray/common/task/task_spec.h`): everything a raylet/worker needs to
schedule and execute a task, including ownership info for the result path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID, WorkerID


class TaskType(Enum):
    NORMAL = 0
    ACTOR_CREATION = 1
    ACTOR_TASK = 2


@dataclass
class SchedulingStrategy:
    """Where a task may run (cf. python/ray/util/scheduling_strategies.py:15,41)."""

    # "DEFAULT" (hybrid), "SPREAD", or None when pg/node targeted
    name: str = "DEFAULT"
    node_id: Optional[bytes] = None       # NodeAffinity
    soft: bool = False
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    # Function: either a serialized callable (normal tasks / actor creation)
    # or a method name (actor tasks).
    function_blob: Optional[bytes]
    method_name: str
    language_hint: str = "python"
    # Export-once fast lane (reference function_manager.py): when set, the
    # callable's pickle lives in the GCS function table under this content
    # hash and `function_blob` is None — the spec ships O(16 bytes) instead
    # of O(closure). Executors resolve through a per-process LRU with a GCS
    # fetch miss path; `function_blob` survives as the fallback wire format
    # for one-shot/unexportable callables.
    function_id: Optional[bytes] = None

    # Arguments: positional list of either ("value", bytes) inline serialized
    # or ("ref", ObjectID, owner_address) for object refs the executor must
    # resolve before running (cf. reference dependency resolution).
    args: List[Tuple] = field(default_factory=list)
    kwargs_blob: Optional[bytes] = None

    # -1 = dynamic (generator task, num_returns="dynamic"): one declared
    # return (the generator object); item objects are created as the
    # executor yields them (cf. reference _raylet.pyx:178 dynamic returns)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    scheduling: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    max_retries: int = 0
    retry_exceptions: bool = False

    # Ownership: address of the owner's core-worker RPC server, and its id.
    owner_address: str = ""
    owner_worker_id: Optional[WorkerID] = None

    # Lineage for recursive cancellation: the task the SUBMITTER was
    # executing when it submitted this one (None for driver-root submits).
    # Each owner only knows its own children — a recursive cancel walks the
    # tree hop by hop: cancel(A) reaches A's executor, which cancels its
    # pending tasks whose parent_task_id == A, and so on leaf-ward
    # (cf. reference TaskSpec parent_task_id / CancelTask recursive=True).
    parent_task_id: Optional[TaskID] = None

    # Actor fields
    actor_id: Optional[ActorID] = None
    actor_creation_spec: Optional["ActorCreationSpec"] = None
    # incarnation fencing (partition failure domain): the actor RESTART
    # count the caller's handle resolved this call against. The hosting
    # worker refuses a mismatch — a call can never be serviced by a
    # superseded instance that a partition kept alive, and a zombie
    # learning of a newer incarnation self-terminates. None = resolved
    # before the caller learned an incarnation (first call racing
    # creation): accepted by any incarnation.
    actor_incarnation: Optional[int] = None
    sequence_number: int = 0  # per-caller ordering for actor tasks
    caller_id: Optional[WorkerID] = None
    # call-site concurrency-group override (reference actor.py:82
    # method.options(concurrency_group=...)); None = method annotation
    concurrency_group: Optional[str] = None

    # runtime env (conda/pip not supported; env vars + working dir are)
    runtime_env: Optional[dict] = None

    # worker recycling: the executing worker exits after running this
    # function max_calls times (reference remote_function.py _max_calls —
    # bounds leaks from native libraries); 0 = unlimited
    max_calls: int = 0

    # distributed tracing (util/tracing.py, gated on tracing_enabled):
    # (trace_id, parent span_id) stamped at submit so the raylet's lease
    # span and the executor's run/result spans join the submitter's causal
    # tree. None when tracing is off — the spec pays no wire cost.
    trace_ctx: Optional[Tuple[str, str]] = None

    def return_object_ids(self) -> List[ObjectID]:
        n = 1 if self.num_returns == -1 else self.num_returns
        return [ObjectID.for_task_return(self.task_id, i + 1) for i in range(n)]


@dataclass
class ActorCreationSpec:
    actor_id: ActorID
    name: Optional[str]            # named actor (get_actor lookup)
    namespace: str
    max_restarts: int
    max_task_retries: int
    max_concurrency: int
    lifetime: str                  # "non_detached" | "detached"
    # Owning job (stamped by the creating worker): the fate-sharing reap
    # kills a dead job's non-detached actors by this field; detached actors
    # are GCS-owned and ignore it. None only for specs predating the stamp.
    job_id: Optional[JobID] = None
    # cloudpickled class — None when the class rides the function table
    class_blob: Optional[bytes] = None
    # export-once id of the class pickle (same fast lane as
    # TaskSpec.function_id): repeated actor creations of one class ship
    # 16 bytes instead of the class closure
    class_fn_id: Optional[bytes] = None
    init_args: List[Tuple] = field(default_factory=list)
    init_kwargs_blob: Optional[bytes] = None
    resources: Dict[str, float] = field(default_factory=dict)
    scheduling: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    runtime_env: Optional[dict] = None
    # named thread pools: methods annotated (or called) with a group run on
    # that group's threads (reference actor.py:65 concurrency_groups)
    concurrency_groups: Optional[Dict[str, int]] = None
    # incarnation this creation/restart instantiates (stamped by the GCS at
    # dispatch = ActorInfo.num_restarts): the hosting worker adopts it, its
    # replies carry it, and every fence check compares against it
    incarnation: int = 0


class ActorState(Enum):
    PENDING = "PENDING_CREATION"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


@dataclass
class ActorInfo:
    actor_id: ActorID
    name: Optional[str]
    namespace: str
    state: ActorState
    address: str = ""              # actor worker's core-worker RPC address
    node_id: Optional[bytes] = None
    num_restarts: int = 0
    max_restarts: int = 0
    death_cause: str = ""
    class_name: str = ""
