"""Standalone node daemon: `ray_tpu start` entry.

Equivalent of the reference's `ray start` process assembly (SURVEY
appendix A, `python/ray/scripts/scripts.py:529`): `--head` runs GCS +
raylet in this process; otherwise a raylet joins an existing GCS.
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import time


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="ray_tpu start")
    parser.add_argument("--head", action="store_true")
    parser.add_argument("--address", help="GCS address to join (worker node)")
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--num-tpus", type=float, default=None)
    parser.add_argument("--resources", default="{}",
                        help='extra resources JSON, e.g. \'{"TPU": 4}\'')
    parser.add_argument("--labels", default="{}",
                        help='node labels JSON, e.g. \'{"tpu_slice": "s0"}\'')
    parser.add_argument("--object-store-memory", type=int, default=None)
    parser.add_argument("--snapshot-path", default=None,
                        help="legacy file path for GCS persistence "
                             "(head only); prefer --snapshot-uri")
    parser.add_argument("--snapshot-uri", default=None,
                        help="SnapshotStore URI for control-plane HA "
                             "(file:///dir or memory://name, head only): a "
                             "replacement head restores node/actor/PG/KV "
                             "state from it, even on a new address")
    parser.add_argument("--standby", action="store_true",
                        help="run a warm STANDBY head: tail the snapshot "
                             "store (--snapshot-uri required), and take "
                             "over via the lease/fencing-epoch CAS when "
                             "the active head's lease expires or is "
                             "relinquished (sub-second promotion; "
                             "RAY_TPU_HEAD_LEASE_TTL_S tunes the TTL)")
    parser.add_argument("--gcs-port", type=int, default=0,
                        help="fixed GCS port (head only; cluster-launcher "
                             "startup scripts need a known join address)")
    parser.add_argument("--gcs-host", default="127.0.0.1",
                        help="GCS bind host (head only; 0.0.0.0 for "
                             "clusters whose workers join over the network)")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)

    logging.basicConfig(level=args.log_level)
    resources = json.loads(args.resources)
    if args.num_cpus is not None:
        resources["CPU"] = args.num_cpus
    if args.num_tpus is not None:
        resources["TPU"] = args.num_tpus
    labels = json.loads(args.labels)

    from ray_tpu.core.gcs import GcsServer, StandbyHead
    from ray_tpu.core.node import default_node_resources, detect_tpu_labels
    from ray_tpu.core.raylet import Raylet

    stop = {"flag": False}

    def handle(sig, frame):
        stop["flag"] = True

    if args.standby:
        # Standby head process: no raylet, no registrations — just the
        # snapshot tail + lease watch. On promotion it IS the head (its
        # promote_announce re-adopts the fleet); it serves until signaled.
        if not args.snapshot_uri:
            parser.error("--standby requires --snapshot-uri")
        standby = StandbyHead(args.snapshot_uri, host=args.gcs_host,
                              port=args.gcs_port)
        standby.start()
        print(f"ray_tpu STANDBY head tailing {args.snapshot_uri} "
              f"(promotes when the active head's lease lapses)")
        signal.signal(signal.SIGINT, handle)
        signal.signal(signal.SIGTERM, handle)
        announced = False
        while not stop["flag"]:
            time.sleep(0.2)
            promoted = standby.promoted
            if promoted is not None and not announced:
                announced = True
                print(f"standby PROMOTED to active head. "
                      f"GCS address: {promoted.address} "
                      f"(epoch {promoted.fence_epoch})")
        standby.stop()
        if standby.promoted is not None:
            standby.promoted.stop()
        return

    labels = {**detect_tpu_labels(), **labels}
    gcs_address = args.address
    gcs = None
    if args.head:
        gcs = GcsServer(snapshot_path=args.snapshot_path,
                        snapshot_uri=args.snapshot_uri,
                        port=args.gcs_port, host=args.gcs_host)
        # rolling upgrade: when a promoted standby fences this head, exit
        # cleanly instead of serving a dead epoch
        gcs.on_fenced = lambda: stop.__setitem__("flag", True)
        gcs_address = gcs.start()
        print(f"ray_tpu head started. GCS address: {gcs_address}")
        print(f"Connect with: ray_tpu.init(address=\"{gcs_address}\")")
    elif not gcs_address:
        parser.error("either --head or --address is required")

    raylet = Raylet(
        gcs_address=gcs_address,
        resources=default_node_resources(None, resources),
        labels=labels,
        object_store_memory=args.object_store_memory,
    )
    raylet.allow_chaos_kill = True  # standalone daemon: kill-random-node ok
    raylet.ship_spans = True        # no worker buffer here: ship our ring
    raylet.start()
    print(f"raylet started on node {raylet.node_id.hex()[:12]} "
          f"({raylet.address})")

    signal.signal(signal.SIGINT, handle)
    signal.signal(signal.SIGTERM, handle)
    while not stop["flag"]:
        time.sleep(0.5)
    raylet.stop()
    if gcs is not None:
        gcs.stop()


if __name__ == "__main__":
    main()
