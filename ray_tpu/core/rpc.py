"""Message-passing RPC layer used by all framework daemons.

Equivalent role to the reference's gRPC wrappers (`src/ray/rpc/`): every
daemon (GCS, raylet, worker) hosts an `RpcServer`; clients hold persistent
connections with pipelined request/response plus server->client pushes (the
push channel is what pubsub and task dispatch ride on, replacing the
reference's long-poll `src/ray/pubsub/` + streaming gRPC).

Design: an asyncio server running on a dedicated thread per process;
synchronous thread-safe clients (a reader thread demultiplexes responses and
pushes). Frames are length-prefixed pickles — the trust model matches the
reference (cluster-internal, same-user processes).
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import socket
import struct
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)

# Frame types
REQ, REP, ERR, PUSH = 0, 1, 2, 3


# --------------------------------------------------------------------------
# Deterministic fault injection (chaos testing without timing luck).
#
# Rules fire at the client SEND boundary of named RPC methods, before the
# bytes reach the socket — the same cut points a real network fault hits.
# The rule spec and RNG seed come from config (RAY_TPU_FAULT_INJECTION_SPEC
# / _SEED env vars, so spawned workers inherit them) or from
# install_fault_injector() for in-process tests. Spec grammar (";" or ","
# separated; <method> is an RPC name or "*"):
#
#   drop:<method>[:<prob>]        message lost (a call raises
#                                 RpcDisconnected; a notify vanishes)
#   delay:<method>:<ms>[:<prob>]  sender stalls before the write
#   sever_once:<method>           connection cut at the first match, then
#                                 the rule disarms (one deterministic cut)
#   sever:<method>[:<prob>]       connection cut per matching send
#   partition:<a>|<b>[:<prob>]    bidirectional blackhole between the named
#                                 node GROUPS a and b: any send whose origin
#                                 resolves into one group and destination
#                                 into the other is dropped (every method).
#                                 Group membership = sets of node endpoint
#                                 addresses ("host:port") plus the literal
#                                 "store" (the snapshot/lease store — so a
#                                 head-in-minority partition starves its
#                                 lease renewals and PR 11's standby fencing
#                                 takes over). Members come from
#                                 define_group() (in-process harnesses) or
#                                 the RAY_TPU_FAULT_PARTITION_GROUPS env
#                                 ("a=addr+addr;b=addr+store") so spawned
#                                 workers inherit the topology. prob < 1.0
#                                 models a flaky (gray) link rather than a
#                                 clean cut. Heal with FaultInjector.heal().
#   fs:<site>:<mode>[:<prob>]     filesystem fault at a named site in the
#                                 storage plane (object_store.py calls
#                                 fs_fault(site) at its spill IO
#                                 boundaries). Sites: spill_write,
#                                 spill_restore (or "*"). Modes:
#                                   enospc   OSError(ENOSPC) — disk full
#                                   eio      OSError(EIO) — media error
#                                   torn     the committed file is
#                                            truncated mid-payload (a
#                                            crash between write and
#                                            fsync; restore-side: short
#                                            read)
#                                   bitflip  one payload byte corrupted
#                                            after checksumming
#                                 Composable with drop/sever/partition
#                                 rules; seeded like everything else.
#
# Determinism: one seeded RNG drives every probabilistic decision, so a
# single-threaded call sequence replays exactly under the same seed.
# Prob-1.0 rules (drop/sever_once/delay without prob) are deterministic
# regardless of threading.
#
# Partition sidedness: every long-lived client carries the NODE identity of
# its owner (`origin=` — a raylet's own server address; for workers and
# drivers, their raylet's address, so partitioning a node group cuts that
# node's worker traffic too). Destinations resolve by the dialed address.
# A send with an unknown side (an address in neither group) passes through:
# partitions cut between named groups, never "everything else".
#
# Named socket-less points (fault_point below) for boundaries that are not
# a single RPC send:
#   serve_replica_call   router -> replica submission (serve failover)
#   lease_renew          active head's lease-renewal WRITE (head_lease.py):
#                        drop it and the lease expires under a healthy head
#                        — the deterministic trigger for standby promotion.
#                        Carries origin=<head address>, dest="store" so a
#                        partition that cuts the head from the store side
#                        starves the lease exactly like a real net split.
# promote_announce needs no fault_point: it is a real client RPC, so
# drop/sever rules hit its send boundary by method name.


# filesystem fault modes injectable at fs:<site> boundaries
FS_FAULT_MODES = ("enospc", "eio", "torn", "bitflip")


class _FaultRule:
    __slots__ = ("action", "method", "prob", "delay_s", "armed", "hits",
                 "group_a", "group_b", "fs_mode")

    def __init__(self, action: str, method: str, prob: float = 1.0,
                 delay_s: float = 0.0, group_a: str = "", group_b: str = "",
                 fs_mode: str = ""):
        self.action = action
        self.method = method
        self.prob = prob
        self.delay_s = delay_s
        self.armed = True
        self.hits = 0
        self.group_a = group_a
        self.group_b = group_b
        self.fs_mode = fs_mode

    def matches(self, method: str) -> bool:
        if not self.armed:
            return False
        if self.action == "partition":
            return True  # partitions blackhole every method between groups
        return self.method == "*" or self.method == method

    def __repr__(self):
        if self.action == "partition":
            return (f"_FaultRule(partition:{self.group_a}|{self.group_b} "
                    f"prob={self.prob} armed={self.armed} hits={self.hits})")
        if self.action == "fs":
            return (f"_FaultRule(fs:{self.method}:{self.fs_mode} "
                    f"prob={self.prob} armed={self.armed} hits={self.hits})")
        return (f"_FaultRule({self.action}:{self.method} prob={self.prob} "
                f"delay={self.delay_s}s hits={self.hits})")


class FaultInjector:
    def __init__(self, spec: str, seed: int = 0,
                 groups: Optional[Dict[str, set]] = None):
        import random as _random

        self.spec = spec
        self.seed = seed
        self._rng = _random.Random(seed)
        self._lock = threading.Lock()
        # partition group membership: name -> set of node endpoint
        # addresses (+ the literal "store"); env-inherited so worker
        # subprocesses share the topology, define_group() for harnesses
        self.groups: Dict[str, set] = {
            name: set(members) for name, members in (groups or {}).items()}
        self.groups.update(self._parse_groups(
            os.environ.get("RAY_TPU_FAULT_PARTITION_GROUPS", "")))
        self.rules = [self._parse_rule(r) for r in
                      spec.replace(",", ";").split(";") if r.strip()]
        self.stats: Dict[str, int] = {"drop": 0, "delay": 0, "sever": 0,
                                      "partition": 0, "fs": 0}

    @staticmethod
    def _parse_groups(text: str) -> Dict[str, set]:
        """"a=host:p1+host:p2;b=host:p3+store" -> {"a": {...}, "b": {...}}
        ("+" separates members because addresses contain ":")."""
        out: Dict[str, set] = {}
        for part in text.split(";"):
            part = part.strip()
            if not part:
                continue
            name, _, members = part.partition("=")
            out[name.strip()] = {m.strip() for m in members.split("+")
                                 if m.strip()}
        return out

    @staticmethod
    def _parse_rule(text: str) -> "_FaultRule":
        parts = [p.strip() for p in text.strip().split(":")]
        action = parts[0]
        if action not in ("drop", "delay", "sever", "sever_once",
                          "partition", "fs"):
            raise ValueError(f"unknown fault action {action!r} in {text!r}")
        if len(parts) < 2 or not parts[1]:
            raise ValueError(f"fault rule {text!r} needs a method name")
        if action == "fs":
            if len(parts) < 3 or parts[2] not in FS_FAULT_MODES:
                raise ValueError(
                    f"fs rule {text!r} needs a mode in {FS_FAULT_MODES} "
                    f"('fs:<site>:<mode>[:<prob>]')")
            prob = float(parts[3]) if len(parts) > 3 else 1.0
            return _FaultRule("fs", parts[1], prob=prob, fs_mode=parts[2])
        if action == "partition":
            a, sep, b = parts[1].partition("|")
            if not sep or not a.strip() or not b.strip():
                raise ValueError(
                    f"partition rule {text!r} needs two group names "
                    f"('partition:<a>|<b>[:<prob>]')")
            prob = float(parts[2]) if len(parts) > 2 else 1.0
            return _FaultRule("partition", "*", prob=prob,
                              group_a=a.strip(), group_b=b.strip())
        method = parts[1]
        if action == "delay":
            if len(parts) < 3:
                raise ValueError(f"delay rule {text!r} needs milliseconds")
            return _FaultRule("delay", method,
                             prob=float(parts[3]) if len(parts) > 3 else 1.0,
                             delay_s=float(parts[2]) / 1000.0)
        prob = float(parts[2]) if len(parts) > 2 else 1.0
        return _FaultRule(action, method, prob=prob)

    # ------------------------------------------------------- partition API
    def define_group(self, name: str, members) -> None:
        """(Re)define a partition group's membership: node endpoint
        addresses ("host:port") and/or the literal "store"."""
        with self._lock:
            self.groups[name] = set(members)

    def partition(self, group_a: str, group_b: str,
                  prob: float = 1.0) -> "_FaultRule":
        """Install (or re-arm) a partition rule between two named groups
        at runtime — the harness-side sibling of the spec grammar."""
        with self._lock:
            for rule in self.rules:
                if (rule.action == "partition"
                        and {rule.group_a, rule.group_b}
                        == {group_a, group_b}):
                    rule.armed = True
                    rule.prob = prob
                    return rule
            rule = _FaultRule("partition", "*", prob=prob,
                              group_a=group_a, group_b=group_b)
            self.rules.append(rule)
            return rule

    # ------------------------------------------------------ filesystem API
    def fs(self, site: str, mode: str, prob: float = 1.0) -> "_FaultRule":
        """Install (or re-arm) an fs:<site>:<mode> rule at runtime — the
        harness-side sibling of the spec grammar. Disarm the returned
        rule (.armed = False) to close the fault window."""
        if mode not in FS_FAULT_MODES:
            raise ValueError(f"fs mode {mode!r} not in {FS_FAULT_MODES}")
        with self._lock:
            for rule in self.rules:
                if (rule.action == "fs" and rule.method == site
                        and rule.fs_mode == mode):
                    rule.armed = True
                    rule.prob = prob
                    return rule
            rule = _FaultRule("fs", site, prob=prob, fs_mode=mode)
            self.rules.append(rule)
            return rule

    def fs_fault(self, site: str) -> Optional[str]:
        """Evaluate fs rules at a named storage-IO site; returns the fault
        mode to inject ("enospc"/"eio"/"torn"/"bitflip") or None. First
        armed matching rule that passes its probability roll wins."""
        for rule in self.rules:
            if rule.action != "fs" or not rule.matches(site):
                continue
            with self._lock:
                if not rule.armed:
                    continue
                if rule.prob < 1.0 and self._rng.random() >= rule.prob:
                    continue
                rule.hits += 1
                self.stats["fs"] += 1
            return rule.fs_mode
        return None

    def heal(self) -> int:
        """Heal every partition: disarm all partition rules (other rule
        kinds are untouched — partitions compose with drop/delay/sever).
        Returns the number of rules disarmed."""
        healed = 0
        with self._lock:
            for rule in self.rules:
                if rule.action == "partition" and rule.armed:
                    rule.armed = False
                    healed += 1
        if healed:
            logger.warning("fault injection: %d partition rule(s) healed",
                           healed)
        return healed

    def _partition_severed(self, rule: "_FaultRule", origin: Optional[str],
                           dest: Optional[str]) -> bool:
        """Does (origin -> dest) straddle this rule's two groups? Unknown
        sides (None, or an address in neither group) never match."""
        if origin is None or dest is None:
            return False
        a = self.groups.get(rule.group_a, ())
        b = self.groups.get(rule.group_b, ())
        return ((origin in a and dest in b)
                or (origin in b and dest in a))

    def partition_drop(self, origin: Optional[str],
                       dest: Optional[str]) -> bool:
        """THE partition evaluator — shared by client sends (on_send) and
        boundaries that are not client sends (server->client pushes, e.g.
        GCS pubsub fan-out). True when the (origin, dest) pair is
        currently blackholed: a blackhole, not a cut — connections stay
        up and every message into them is lost, the asymmetric-
        reachability model. Never raises."""
        for rule in self.rules:
            if rule.action != "partition" or not rule.armed:
                continue
            if not self._partition_severed(rule, origin, dest):
                continue
            with self._lock:
                if not rule.armed:
                    continue
                if rule.prob < 1.0 and self._rng.random() >= rule.prob:
                    continue
                rule.hits += 1
                self.stats["partition"] += 1
            return True
        return False

    def on_send(self, method: str, client: Optional["RpcClient"],
                origin: Optional[str] = None,
                dest: Optional[str] = None) -> Optional[str]:
        """Apply matching rules; returns "drop" when the message must be
        lost, raises RpcDisconnected after severing the connection.
        `client` may be None for socket-less named injection points
        (`fault_point`): sever then cuts nothing but still raises.
        `origin`/`dest` resolve partition sidedness (defaulted from the
        client's origin label and dialed address); partitions are judged
        first — a blackholed send never reaches the per-method rules."""
        if client is not None:
            if origin is None:
                origin = client.origin
            if dest is None:
                dest = client.address
        if self.partition_drop(origin, dest):
            return "drop"
        for rule in self.rules:
            if (rule.action in ("partition", "fs")
                    or not rule.matches(method)):
                continue
            with self._lock:
                if not rule.armed:
                    continue
                fire = rule.prob >= 1.0 or self._rng.random() < rule.prob
                if not fire:
                    continue
                rule.hits += 1
                if rule.action == "sever_once":
                    rule.armed = False
            if rule.action == "delay":
                self.stats["delay"] += 1
                time.sleep(rule.delay_s)
            elif rule.action == "drop":
                self.stats["drop"] += 1
                return "drop"
            else:  # sever / sever_once
                self.stats["sever"] += 1
                addr = "(no socket)"
                if client is not None:
                    client.close()
                    addr = client.address
                raise RpcDisconnected(
                    f"[fault-injection seed={self.seed}] severed "
                    f"{method} to {addr}")
        return None


def read_gcs_address_file() -> Optional[str]:
    """The published GCS address from config `gcs_address_file`, or None
    when unset/unreadable/empty — the shared first hop of every
    control-plane re-resolution chain (raylet, worker, driver). The writer
    (GcsServer._write_address_file) swaps atomically through an fsynced
    tmp file, and an empty/whitespace read here means "no answer yet —
    retry with the last-known address", never "connect to ''": together
    they make a reader racing a mid-failover writer safe."""
    from ray_tpu.core.config import get_config

    path = get_config().gcs_address_file
    if not path:
        return None
    try:
        with open(path) as f:
            addr = f.read().strip()
    except OSError:
        return None
    return addr or None


_fault_injector: Optional[FaultInjector] = None
_fault_checked = False
_fault_lock = threading.Lock()


def install_fault_injector(spec: str, seed: int = 0,
                           groups: Optional[Dict[str, set]] = None
                           ) -> FaultInjector:
    """Programmatic injection for in-process tests. Returns the injector
    (its .stats/.rules expose hit counts for assertions). `groups` seeds
    partition group membership (see FaultInjector.define_group)."""
    global _fault_injector, _fault_checked
    inj = FaultInjector(spec, seed, groups=groups)
    with _fault_lock:
        _fault_injector = inj
        _fault_checked = True
    logger.warning("fault injection ACTIVE: spec=%r seed=%d "
                   "(reproduce with RAY_TPU_FAULT_INJECTION_SPEC/"
                   "RAY_TPU_FAULT_INJECTION_SEED)", spec, seed)
    return inj


def fault_point(name: str, origin: Optional[str] = None,
                dest: Optional[str] = None) -> None:
    """Named, socket-less injection point for boundaries that are not a
    single RPC send (e.g. the serve router's replica-call submission,
    name `serve_replica_call`). Rules target it exactly like an RPC
    method: `drop`/`sever`/`sever_once` raise RpcDisconnected here (the
    caller's failover path takes over), `delay` stalls the caller. A
    no-op (zero overhead beyond one None check) without an injector.
    `origin`/`dest` give partition rules a sidedness to judge (e.g. the
    head's lease renewal passes origin=<head address>, dest="store")."""
    inj = get_fault_injector()
    if inj is None:
        return
    if inj.on_send(name, None, origin=origin, dest=dest) == "drop":
        raise RpcDisconnected(
            f"[fault-injection seed={inj.seed}] dropped {name}")


def fs_fault(site: str) -> Optional[str]:
    """Named filesystem injection point (sites: spill_write,
    spill_restore). Returns the fault mode the caller must simulate
    ("enospc"/"eio"/"torn"/"bitflip") or None. Unlike fault_point() this
    never raises — the storage plane turns the mode into the right OSError
    or corruption itself, so the fault exercises the REAL error-handling
    path, not an injected exception type. Zero overhead uninjected."""
    inj = get_fault_injector()
    if inj is None:
        return None
    return inj.fs_fault(site)


def clear_fault_injector() -> None:
    global _fault_injector, _fault_checked
    with _fault_lock:
        _fault_injector = None
        _fault_checked = True


def get_fault_injector() -> Optional[FaultInjector]:
    """The active injector, initializing once from config (env-driven:
    spawned worker processes inherit the spec + seed and print the seed,
    so a failing chaos run is reproducible)."""
    global _fault_injector, _fault_checked
    if _fault_checked:
        return _fault_injector
    with _fault_lock:
        if _fault_checked:
            return _fault_injector
        try:
            from ray_tpu.core.config import get_config

            cfg = get_config()
            if cfg.fault_injection_spec:
                _fault_injector = FaultInjector(cfg.fault_injection_spec,
                                                cfg.fault_injection_seed)
                logger.warning(
                    "fault injection ACTIVE from config: spec=%r seed=%d",
                    cfg.fault_injection_spec, cfg.fault_injection_seed)
        except Exception:
            logger.exception("fault injector init failed; disabled")
        _fault_checked = True
        return _fault_injector

# ------------------------------------------------------ rpc latency metrics
# One central instrumentation site for EVERY request/reply RPC in the
# system (reference: per-service gRPC latency metrics; here the single
# client-send boundary makes one histogram cover them all). Observed on
# the reply via a Future callback, so the send path pays one perf_counter
# read; exported through the standard Prometheus registry and scraped by
# the dashboard like any other series.
_rpc_latency_hist = None


def _observe_rpc_latency(method: str, seconds: float) -> None:
    global _rpc_latency_hist
    try:
        h = _rpc_latency_hist
        if h is None:
            from ray_tpu.util.metrics import get_or_create

            h = _rpc_latency_hist = get_or_create(
                "histogram", "ray_tpu_rpc_latency_seconds",
                "request/reply RPC round-trip latency by method",
                boundaries=(0.0005, 0.002, 0.01, 0.05, 0.25, 1, 5, 30),
                tag_keys=("method",))
        h.observe(seconds, tags={"method": method})
    except Exception:  # metrics must never fail an RPC
        logger.debug("rpc latency observe failed", exc_info=True)


_HDR = struct.Struct("!BQI")  # type, request_id, method-name length


def _encode(msg_type: int, req_id: int, method: str, payload: Any) -> bytes:
    m = method.encode()
    body = pickle.dumps(payload, protocol=5)
    frame = _HDR.pack(msg_type, req_id, len(m)) + m + body
    return struct.pack("!Q", len(frame)) + frame


def _decode(frame: bytes):
    msg_type, req_id, mlen = _HDR.unpack_from(frame, 0)
    off = _HDR.size
    method = frame[off : off + mlen].decode()
    payload = pickle.loads(frame[off + mlen :])
    return msg_type, req_id, method, payload


class RpcDisconnected(ConnectionError):
    pass


class ServerConnection:
    """Server-side view of one client connection; supports pushes."""

    def __init__(self, server: "RpcServer", writer: asyncio.StreamWriter, peer: str):
        self._server = server
        self._writer = writer
        self.peer = peer
        self.ident: Any = None  # set by a `hello` handler if the app wants
        # NODE identity the subscriber declared (subscribe payload
        # "origin"): lets server->client pushes (pubsub fan-out) honor
        # partition rules — a blackholed side gets no pushes either
        self.origin: Optional[str] = None
        self.alive = True
        self.on_close: list[Callable[["ServerConnection"], None]] = []

    def push(self, method: str, payload: Any) -> None:
        """Send a one-way message to the client (thread-safe)."""
        data = _encode(PUSH, 0, method, payload)
        self._server._loop.call_soon_threadsafe(self._write, data)

    def reply(self, req_id: int, payload: Any, is_error: bool = False) -> None:
        data = _encode(ERR if is_error else REP, req_id, "", payload)
        self._server._loop.call_soon_threadsafe(self._write, data)

    def _write(self, data: bytes) -> None:
        if self.alive:
            try:
                self._writer.write(data)
            except (OSError, RuntimeError):  # closed transport/loop
                self.alive = False


class RpcServer:
    """Asyncio RPC server on a dedicated thread.

    Handlers: `fn(conn, payload) -> result` (sync, runs on loop — keep fast)
    or `async fn(conn, payload)`. A handler may return `Deferred` to reply
    later via `conn.reply(req_id, ...)` (used for blocking ops like object
    gets and worker leases).
    """

    class Deferred:
        """Sentinel: handler will reply asynchronously via conn.reply(req_id)."""

    DEFERRED = Deferred()

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._requested_port = port
        self._handlers: Dict[str, Callable] = {}
        self._loop: asyncio.AbstractEventLoop = None  # type: ignore
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: int = 0
        self.connections: list[ServerConnection] = []
        self._started = threading.Event()
        self._stopped = False

    @property
    def address(self) -> str:
        return f"{self._host}:{self.port}"

    def register(self, method: str, fn: Callable) -> None:
        self._handlers[method] = fn

    def register_all(self, obj: Any, prefix: str = "") -> None:
        """Register every `rpc_*` method of `obj` under its suffix name."""
        for name in dir(obj):
            if name.startswith("rpc_"):
                self.register(prefix + name[4:], getattr(obj, name))

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="rpc-server", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("RPC server failed to start")

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def _serve():
            self._server = await asyncio.start_server(
                self._handle_conn, self._host, self._requested_port)
            self.port = self._server.sockets[0].getsockname()[1]
            self._started.set()

        self._loop.run_until_complete(_serve())
        try:
            self._loop.run_forever()
        finally:
            try:
                self._loop.run_until_complete(self._loop.shutdown_asyncgens())
            except RuntimeError:
                pass  # loop already stopping
            self._loop.close()

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        peer = str(writer.get_extra_info("peername"))
        conn = ServerConnection(self, writer, peer)
        self.connections.append(conn)
        try:
            while True:
                hdr = await reader.readexactly(8)
                (n,) = struct.unpack("!Q", hdr)
                frame = await reader.readexactly(n)
                msg_type, req_id, method, payload = _decode(frame)
                handler = self._handlers.get(method)
                if handler is None:
                    if msg_type == REQ:
                        conn.reply(req_id, f"no such method: {method}", is_error=True)
                    continue
                try:
                    if asyncio.iscoroutinefunction(handler):
                        result = await handler(conn, req_id, payload)
                    else:
                        result = handler(conn, req_id, payload)
                    if msg_type == REQ and not isinstance(result, RpcServer.Deferred):
                        conn.reply(req_id, result)
                except Exception as e:  # handler error -> error reply
                    logger.exception("handler %s failed", method)
                    if msg_type == REQ:
                        import traceback

                        conn.reply(req_id, f"{e}\n{traceback.format_exc()}", is_error=True)
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError):
            pass
        except Exception:
            if not self._stopped:
                logger.exception("connection error from %s", peer)
        finally:
            conn.alive = False
            try:
                self.connections.remove(conn)
            except ValueError:
                pass
            for cb in conn.on_close:
                try:
                    cb(conn)
                except Exception:
                    logger.exception("on_close callback failed")
            try:
                writer.close()
            except OSError:
                pass

    def call_soon(self, fn: Callable, *args) -> None:
        """Schedule `fn` on the server loop (thread-safe)."""
        self._loop.call_soon_threadsafe(fn, *args)

    def call_later(self, delay: float, fn: Callable, *args):
        return self._loop.call_soon_threadsafe(
            lambda: self._loop.call_later(delay, fn, *args)
        )

    def stop(self) -> None:
        self._stopped = True
        if self._loop and self._loop.is_running():
            def _shutdown():
                if self._server:
                    self._server.close()
                for conn in list(self.connections):
                    conn.alive = False
                    try:
                        conn._writer.close()
                    except OSError:
                        pass
                self._loop.stop()
            try:
                self._loop.call_soon_threadsafe(_shutdown)
            except RuntimeError:
                pass
        if self._thread:
            self._thread.join(timeout=5)


class RpcClient:
    """Thread-safe synchronous client with pipelining and push dispatch."""

    def __init__(self, address: str, push_handler: Optional[Callable[[str, Any], None]] = None,
                 connect_timeout: float = 30.0, on_disconnect: Optional[Callable[[], None]] = None,
                 origin: Optional[str] = None):
        host, port = address.rsplit(":", 1)
        self.address = address
        # NODE identity of this client's owner (a daemon's own server
        # address; a worker's/driver's raylet address) — what partition
        # rules use to decide which side of a net split a send starts from
        self.origin = origin
        self._sock = socket.create_connection((host, int(port)), timeout=connect_timeout)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._next_id = 1
        self._id_lock = threading.Lock()
        self._push_handler = push_handler
        self._on_disconnect = on_disconnect
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop, name="rpc-client-reader", daemon=True)
        self._reader.start()

    def _read_loop(self):
        f = self._sock.makefile("rb")
        try:
            while True:
                hdr = f.read(8)
                if len(hdr) < 8:
                    break
                (n,) = struct.unpack("!Q", hdr)
                frame = f.read(n)
                if len(frame) < n:
                    break
                msg_type, req_id, method, payload = _decode(frame)
                if msg_type == PUSH:
                    if self._push_handler is not None:
                        try:
                            self._push_handler(method, payload)
                        except Exception:
                            logger.exception("push handler failed for %s", method)
                else:
                    fut = self._pending.pop(req_id, None)
                    if fut is not None:
                        if msg_type == ERR:
                            fut.set_exception(RpcCallError(str(payload)))
                        else:
                            fut.set_result(payload)
        except Exception:
            if not self._closed:
                logger.debug("rpc client read loop ended", exc_info=True)
        finally:
            self._closed = True
            err = RpcDisconnected(f"connection to {self.address} lost")
            for fut in list(self._pending.values()):
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            if self._on_disconnect is not None:
                try:
                    self._on_disconnect()
                except Exception:
                    pass

    def _send(self, data: bytes) -> None:
        if self._closed:
            raise RpcDisconnected(f"connection to {self.address} closed")
        with self._wlock:
            self._sock.sendall(data)

    def call_future(self, method: str, payload: Any = None) -> Future:
        inj = get_fault_injector()
        if inj is not None and inj.on_send(method, self) == "drop":
            # the request never reaches the wire: to the caller that is a
            # lost link (no reply would ever arrive)
            raise RpcDisconnected(
                f"[fault-injection seed={inj.seed}] dropped call "
                f"{method} to {self.address}")
        with self._id_lock:
            req_id = self._next_id
            self._next_id += 1
        fut: Future = Future()
        self._pending[req_id] = fut
        try:
            self._send(_encode(REQ, req_id, method, payload))
        except Exception:
            self._pending.pop(req_id, None)
            raise
        t0 = time.perf_counter()
        fut.add_done_callback(
            lambda f, m=method, t=t0: _observe_rpc_latency(
                m, time.perf_counter() - t))
        return fut

    def call(self, method: str, payload: Any = None, timeout: Optional[float] = None) -> Any:
        return self.call_future(method, payload).result(timeout=timeout)

    def notify(self, method: str, payload: Any = None) -> None:
        """One-way message (no response expected)."""
        inj = get_fault_injector()
        if inj is not None and inj.on_send(method, self) == "drop":
            return  # one-way message silently lost, like the real fault
        self._send(_encode(PUSH, 0, method, payload))

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # never connected / already reset
        try:
            self._sock.close()
        except OSError:
            pass


class RpcCallError(RuntimeError):
    """Remote handler raised; message contains remote traceback."""


class ReconnectingClient:
    """Client that transparently re-establishes a lost connection (reference
    retryable gRPC clients, `src/ray/rpc/grpc_util.h`): `call()` retries
    across one reconnect, `notify()` is best-effort, and `on_reconnect(raw)`
    replays session state (registrations, subscriptions) on every fresh
    connection before other calls proceed. Built for long-lived links to the
    control plane, which may restart (GCS fault tolerance) or be REPLACED on
    a new address (control-plane HA): `resolve()` — when given — is invoked
    before every connection attempt and may return an updated address (from
    the GCS address file, an in-band announce, or the local raylet), so the
    link follows the head wherever it comes back. Reconnect attempts sleep
    with exponential backoff + full jitter (util/backoff.py): a replacement
    head sees the whole fleet re-register without a synchronized stampede."""

    def __init__(self, address: str,
                 push_handler: Optional[Callable[[str, Any], None]] = None,
                 timeout: float = 30.0,
                 on_reconnect: Optional[Callable[["RpcClient"], None]] = None,
                 reconnect_timeout: float = 30.0,
                 resolve: Optional[Callable[[], Optional[str]]] = None,
                 origin: Optional[str] = None):
        self.address = address
        self.origin = origin
        self._push_handler = push_handler
        self._on_reconnect = on_reconnect
        self._reconnect_timeout = reconnect_timeout
        self._resolve = resolve
        self._lock = threading.Lock()
        self._closed = False
        self._reconnecting = False
        self._client = self._connect(timeout)

    def _backoff(self):
        from ray_tpu.core.config import get_config
        from ray_tpu.util.backoff import ExponentialBackoff

        cfg = get_config()
        return ExponentialBackoff(
            base_s=cfg.reconnect_backoff_base_ms / 1000.0,
            cap_s=cfg.reconnect_backoff_cap_ms / 1000.0)

    def _resolved_address(self) -> str:
        if self._resolve is not None:
            try:
                addr = self._resolve()
            except Exception:
                logger.debug("address resolve failed; keeping %s",
                             self.address, exc_info=True)
                addr = None
            if addr and addr != self.address:
                logger.info("control-plane address re-resolved: %s -> %s",
                            self.address, addr)
                self.address = addr
        return self.address

    def _connect(self, timeout: float) -> RpcClient:
        # Eager recovery: a drop triggers a background reconnect so even a
        # process that never initiates calls (an idle actor worker) promptly
        # re-registers with a restarted control plane. The address is
        # RE-resolved on every attempt — a head replacement may publish its
        # new address while we are mid-retry against the old one.
        deadline = time.monotonic() + timeout
        backoff = self._backoff()
        last: Exception | None = None
        while True:
            addr = self._resolved_address()
            try:
                return RpcClient(
                    addr, push_handler=self._push_handler,
                    on_disconnect=self._schedule_reconnect,
                    connect_timeout=min(timeout, 5.0),
                    origin=self.origin)
            except (ConnectionRefusedError, OSError) as e:
                last = e
            remaining = deadline - time.monotonic()
            if self._closed or remaining <= 0:
                raise ConnectionError(
                    f"could not connect to {self.address} within "
                    f"{timeout}s: {last}")
            time.sleep(min(max(0.02, backoff.next_delay()), remaining))

    def _schedule_reconnect(self) -> None:
        if self._closed or self._reconnecting:
            return

        def run():
            self._reconnecting = True
            try:
                backoff = self._backoff()
                backoff.sleep()
                while not self._closed:
                    try:
                        self._live_client()
                        return
                    except Exception:
                        backoff.sleep()
            finally:
                self._reconnecting = False

        threading.Thread(target=run, name="rpc-reconnect", daemon=True).start()

    def _live_client(self) -> RpcClient:
        cli = self._client
        if cli is not None and not cli.closed:
            return cli
        with self._lock:
            if self._closed:
                raise RpcDisconnected(f"client to {self.address} closed")
            cli = self._client
            if cli is not None and not cli.closed:
                return cli
            cli = self._connect(self._reconnect_timeout)
            try:
                if self._closed:
                    # close() raced the reconnect: never install or register
                    # a connection for a torn-down component (ghost nodes).
                    raise RpcDisconnected(f"client to {self.address} closed")
                # Replay registrations while holding the lock so concurrent
                # calls can't race ahead of re-registration on the new link.
                # A FAILED replay must not install the client: the process
                # would be connected-but-unregistered forever (heartbeats
                # accepted, node absent from the cluster view).
                if self._on_reconnect is not None:
                    self._on_reconnect(cli)
            except Exception:
                cli.close()
                raise
            self._client = cli
            return cli

    def call(self, method: str, payload: Any = None,
             timeout: Optional[float] = None) -> Any:
        for attempt in (0, 1):
            try:
                return self._live_client().call(method, payload, timeout=timeout)
            except RpcDisconnected:
                if attempt:
                    raise
        raise RpcDisconnected(f"call {method} to {self.address} failed")

    def notify(self, method: str, payload: Any = None) -> None:
        """Best-effort AND non-blocking: while the link is down the message
        is dropped and a background reconnect is kicked off — callers are
        fire-and-forget paths (task events, resource reports) that must
        never stall an exec thread or RPC loop for a connect timeout."""
        self.try_notify(method, payload)

    def try_notify(self, method: str, payload: Any = None) -> bool:
        """notify() that reports whether the message reached the socket:
        False means the link is down (message dropped, background reconnect
        kicked) so the caller can requeue. Still non-blocking; a write that
        lands in a dying socket's buffer may yet be lost — this detects the
        common down-link window (e.g. a GCS restart), not every loss."""
        cli = self._client
        if cli is None or cli.closed:
            self._schedule_reconnect()
            return False
        try:
            cli.notify(method, payload)
            return True
        except Exception:
            self._schedule_reconnect()
            return False

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        # Deliberately not taking _lock: an in-flight reconnect may hold it
        # for a full connect timeout; it re-checks _closed post-connect and
        # self-closes instead of installing.
        self._closed = True
        cli = self._client
        if cli is not None:
            cli.close()


def connect_with_retry(address: str, timeout: float = 30.0, **kw) -> RpcClient:
    deadline = time.monotonic() + timeout
    last: Exception | None = None
    while time.monotonic() < deadline:
        try:
            return RpcClient(address, **kw)
        except (ConnectionRefusedError, OSError) as e:
            last = e
            time.sleep(0.05)
    raise ConnectionError(f"could not connect to {address} within {timeout}s: {last}")
