"""Head lease + fencing tokens over the SnapshotStore.

The coordination primitive behind the standby head (ROADMAP item 5; the
role etcd/Redis leader election plays for the reference's HA GCS,
`gcs_server.h` + the Ray 2.x GCS fault-tolerance design): the ACTIVE head
holds a TTL lease stored beside the versioned snapshots, renewing it every
ttl/3; a STANDBY head tails the snapshot stream and, when the lease
expires (crash) or is relinquished (rolling upgrade), takes over by
bumping the lease **epoch** — the fencing token.

The epoch is what makes takeover safe on a dumb blob store with no server
side CAS:

  * every ownership CHANGE increments the epoch; renewal never does;
  * acquire() is a compare-and-swap in the only way a keyed blob store
    allows: read (verify expired/expected epoch) -> write (epoch+1) ->
    settle -> re-read and verify we are still the recorded owner. Two
    racing claimants both write, exactly one survives the verify;
  * every fencing-relevant write the OLD head attempts afterwards
    (snapshot save, raylet-facing announce) carries its stale epoch and is
    REJECTED — `check()` raises `LeaseLostError` before a snapshot write,
    and raylets log-and-drop announces whose epoch trails the one they
    adopted. A revived stale head cannot split the brain; its writes
    bounce instead of racing.

`fault_point("lease_renew")` fires before the renewal WRITE (after the
fencing read), so a seeded `drop:lease_renew` rule models lost renewals —
the lease expires under a perfectly healthy head and the standby promotes
— while fencing discovery (reading a bumped epoch) still works.
"""

from __future__ import annotations

import logging
import pickle
import threading
import time
import uuid
from typing import Optional

from ray_tpu.core.snapshot_store import (SnapshotCorruptError, SnapshotStore,
                                         decode_blob, encode_blob)

logger = logging.getLogger(__name__)

# Lives beside the "gcs-<seq>" snapshot keys; VersionedSnapshots skips
# non-numeric tails, so the lease never collides with version pruning.
LEASE_KEY = "gcs-lease"


class LeaseLostError(RuntimeError):
    """The store's lease epoch advanced past ours: a newer head took over.
    The holder is FENCED — it must stop writing and retire."""


class LeaseHeldError(RuntimeError):
    """Acquire refused: another owner's lease is still live."""


def new_owner_token() -> str:
    """Unique per-process-instance owner identity (an address is not
    enough: a restarted head on the same address is a DIFFERENT holder)."""
    return uuid.uuid4().hex[:12]


class HeadLease:
    def __init__(self, store: SnapshotStore, key: str = LEASE_KEY,
                 ttl_s: Optional[float] = None):
        from ray_tpu.core.config import get_config

        self.store = store
        self.key = key
        self.ttl_s = ttl_s if ttl_s is not None \
            else get_config().head_lease_ttl_s
        self._lock = threading.Lock()
        # holder's node endpoint, set by the owning head: gives the
        # lease_renew fault point a partition SIDE — a partition rule
        # cutting this origin from the "store" group starves renewals
        # exactly like a real head-in-minority network split
        self.origin: Optional[str] = None

    # ------------------------------------------------------------------ io
    def read(self) -> Optional[dict]:
        blob = self.store.get(self.key)
        if blob is None:
            return None
        try:
            return pickle.loads(decode_blob(blob))
        except (SnapshotCorruptError, Exception) as e:  # torn/corrupt write
            logger.warning("head lease record unreadable (%s); treating as "
                           "absent", e)
            return None

    def _write(self, record: dict) -> None:
        self.store.put(self.key, encode_blob(
            pickle.dumps(record, protocol=5)))

    # ------------------------------------------------------------ protocol
    def acquire(self, owner: str, expect_epoch: Optional[int] = None,
                force: bool = False, settle_s: float = 0.05,
                floor: int = 0) -> int:
        """Take the lease, bumping the fencing epoch. Without `force` the
        current lease must be expired (or already ours); `expect_epoch`
        additionally demands the epoch we SAW expire is still the recorded
        one (a standby must not promote over a head that renewed in the
        window). `floor` guards against a torn/lost lease RECORD resetting
        the epoch: callers pass (last epoch seen in the snapshot stream)+1
        so the new epoch can never trail one the fleet already adopted.
        Returns the new epoch; raises LeaseHeldError / LeaseLostError when
        the claim is refused or lost to a racer."""
        with self._lock:
            cur = self.read()
            now = time.time()
            if cur is not None and not force and cur.get("owner") != owner:
                if cur.get("expires_at", 0.0) > now:
                    raise LeaseHeldError(
                        f"lease epoch {cur.get('epoch')} held by "
                        f"{cur.get('owner')} for another "
                        f"{cur.get('expires_at', 0.0) - now:.2f}s")
                if expect_epoch is not None \
                        and cur.get("epoch") != expect_epoch:
                    raise LeaseLostError(
                        f"lease advanced to epoch {cur.get('epoch')} past "
                        f"the observed {expect_epoch}")
            epoch = max(
                (int(cur.get("epoch", 0)) + 1) if cur is not None else 1,
                floor)
            self._write({
                "epoch": epoch, "owner": owner,
                "expires_at": now + self.ttl_s, "renewed_at": now,
                "acquired_at": now,
            })
        # CAS verify: on a dumb store two claimants can both write; after a
        # settle window exactly one is the recorded owner.
        if settle_s > 0:
            time.sleep(settle_s)
        check = self.read()
        if check is None or check.get("owner") != owner \
                or check.get("epoch") != epoch:
            raise LeaseLostError(
                f"acquire of epoch {epoch} lost to "
                f"{check.get('owner') if check else 'a deleted record'}")
        return epoch

    def renew(self, owner: str, epoch: int, **extra) -> None:
        """Extend the TTL of a lease we hold. Reads FIRST so a bumped epoch
        is discovered (LeaseLostError -> the holder fences itself) even
        when our own writes are being dropped; the injected `lease_renew`
        fault fires between the fencing read and the write."""
        from ray_tpu.core import rpc

        with self._lock:
            cur = self.read()
            if cur is not None and (
                    int(cur.get("epoch", 0)) > epoch
                    or (int(cur.get("epoch", 0)) == epoch
                        and cur.get("owner") != owner)):
                raise LeaseLostError(
                    f"lease epoch advanced to {cur.get('epoch')} "
                    f"(owner {cur.get('owner')}); this head holds stale "
                    f"epoch {epoch}")
            if cur is not None and cur.get("relinquished"):
                # an in-flight renewal racing drain_lease() must not
                # resurrect the relinquished lease for a full TTL — the
                # whole point of relinquish is "a standby may take over NOW"
                return
            rpc.fault_point("lease_renew", origin=self.origin, dest="store")
            now = time.time()
            rec = {"epoch": epoch, "owner": owner,
                   "expires_at": now + self.ttl_s, "renewed_at": now,
                   "acquired_at": (cur or {}).get("acquired_at", now)}
            rec.update(extra)
            self._write(rec)

    def relinquish(self, owner: str, epoch: int) -> None:
        """Rolling-upgrade handoff: expire the lease NOW (epoch unchanged)
        so a standby promotes immediately instead of waiting out the TTL.
        The caller must stop renewing first."""
        with self._lock:
            cur = self.read()
            if cur is not None and int(cur.get("epoch", 0)) > epoch:
                raise LeaseLostError(
                    f"cannot relinquish epoch {epoch}: store already at "
                    f"{cur.get('epoch')}")
            now = time.time()
            self._write({"epoch": epoch, "owner": owner,
                         "expires_at": now, "renewed_at": now,
                         "relinquished": True,
                         "acquired_at": (cur or {}).get("acquired_at", now)})

    def check(self, epoch: int) -> None:
        """Fencing gate for durable writes: raises LeaseLostError when the
        store's epoch has advanced past `epoch` (a newer head owns the
        state; our write must be rejected, not raced)."""
        cur = self.read()
        if cur is not None and int(cur.get("epoch", 0)) > epoch:
            raise LeaseLostError(
                f"fenced: store lease at epoch {cur.get('epoch')}, "
                f"this head at {epoch}")
