"""Export-once function table: pickle a callable once, ship an id forever.

Equivalent of the reference's function manager
(`python/ray/_private/function_manager.py`): on the first submission of a
callable the submitter exports its cloudpickle blob to a GCS table keyed by
a content hash (`FunctionID`), and every TaskSpec afterwards carries only
the 16-byte id. Executors resolve ids through a per-process LRU of
*deserialized* functions, fetching the blob from the GCS exactly once per
process on a miss. Without this, every `f.remote()` re-runs
`cloudpickle.dumps` and ships the full closure, and every execution re-runs
`cloudpickle.loads` — the dominant control-plane cost for closure-heavy
fine-grained tasks (the Podracer/RL workload class).

The blob-in-spec path survives as a fallback: callables that cannot be
weak-referenced (the export cache must not leak one-shot lambdas) and
clusters with `function_table_enabled=False` ship the pickle inline, and
executors accept either form.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Optional, Tuple

import cloudpickle

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import FunctionID

logger = logging.getLogger(__name__)


class FunctionTableClient:
    """Per-CoreWorker client for the GCS function table: export cache on
    the submitting side, deserialized-function LRU on the executing side
    (one process can be both, e.g. an actor that submits subtasks)."""

    def __init__(self, worker):
        self._worker = worker
        # submitter side: callable -> (fid_bytes, blob). Weak keys so the
        # cache dies with the function object instead of pinning it.
        self._exports: "weakref.WeakKeyDictionary[Any, Tuple[bytes, bytes]]" \
            = weakref.WeakKeyDictionary()
        # fids this process has confirmed into the GCS (blocking put once)
        self._exported_ids: set = set()
        # executor side: fid -> deserialized callable, LRU-capped
        self._cache: "OrderedDict[bytes, Any]" = OrderedDict()
        self._lock = threading.RLock()
        # instrumentation (tests + microbenchmark read these)
        self.pickle_count = 0
        self.cache_hits = 0
        self.cache_misses = 0

    def _job_id_bytes(self) -> Optional[bytes]:
        # The GCS job-ownership index tolerates None (unit-test fakes and
        # pre-connect workers have no job id); a missing attribute must not
        # demote the export to the inline-pickle fallback.
        jid = getattr(self._worker, "job_id", None)
        return jid.binary() if jid is not None else None

    # ------------------------------------------------------------ submitter
    def export(self, obj: Any) -> Tuple[Optional[bytes], Optional[bytes]]:
        """Export a callable/class for a spec. Returns (function_id, None)
        when the blob lives in the GCS table, or (None, blob) for the
        inline-pickle fallback."""
        if not get_config().function_table_enabled:
            return None, cloudpickle.dumps(obj)
        with self._lock:
            try:
                entry = self._exports.get(obj)
            except TypeError:  # unhashable callable: cannot cache safely
                self.pickle_count += 1
                return None, cloudpickle.dumps(obj)
            if entry is None:
                blob = cloudpickle.dumps(obj)
                self.pickle_count += 1
                fid = FunctionID.for_blob(blob).binary()
                entry = (fid, blob)
                try:
                    self._exports[obj] = entry
                except TypeError:
                    # not weak-referenceable: treat as one-shot, ship inline
                    return None, blob
        fid, blob = entry
        try:
            self._ensure_exported(fid, blob)
        except Exception:
            # GCS down or mid-restart: submission must not gain a control-
            # plane liveness dependency it never had — ship the pickle
            # inline this time; the next submission retries the export.
            logger.debug("function export deferred (GCS unreachable)",
                         exc_info=True)
            return None, blob
        return fid, None

    def _ensure_exported(self, fid: bytes, blob: bytes) -> None:
        """Blocking put on FIRST export only: the spec may race ahead of the
        blob over a different connection, so the one-time export must land
        before the task can reach an executor."""
        with self._lock:
            if fid in self._exported_ids:
                return
        self._worker.gcs.call(
            "function_put", {"function_id": fid, "blob": blob,
                             "job_id": self._job_id_bytes()},
            timeout=30)
        with self._lock:
            self._exported_ids.add(fid)

    def replay_exports(self, raw_client) -> None:
        """After a GCS restart, the in-memory function table may be gone:
        re-put every export this process still holds (rides the
        reconnecting client's on_reconnect hook, like job/actor state)."""
        with self._lock:
            entries = list(self._exports.values())
        for fid, blob in entries:
            try:
                raw_client.call("function_put",
                                {"function_id": fid, "blob": blob,
                                 "job_id": self._job_id_bytes()},
                                timeout=30)
            except Exception:
                # Un-mark the export: leaving it in _exported_ids would make
                # every future submission ship an id the (healthy, but
                # fresh) GCS cannot resolve. The next .remote() re-attempts
                # the put through _ensure_exported.
                with self._lock:
                    self._exported_ids.discard(fid)
                logger.debug("function export replay failed", exc_info=True)

    # ------------------------------------------------------------- executor
    def resolve(self, function_id: Optional[bytes],
                blob: Optional[bytes]) -> Any:
        """Resolve a spec's callable: inline blob fallback, else LRU of
        deserialized functions with a GCS fetch on miss."""
        if function_id is None:
            return cloudpickle.loads(blob)
        with self._lock:
            fn = self._cache.get(function_id)
            if fn is not None:
                self._cache.move_to_end(function_id)
                self.cache_hits += 1
                return fn
            self.cache_misses += 1
        fn = cloudpickle.loads(self._fetch(function_id, blob))
        with self._lock:
            self._cache[function_id] = fn
            self._cache.move_to_end(function_id)
            cap = max(1, get_config().function_cache_max_entries)
            while len(self._cache) > cap:
                self._cache.popitem(last=False)
        return fn

    def _fetch(self, fid: bytes, fallback_blob: Optional[bytes]) -> bytes:
        """GCS fetch with a short retry ladder: a submitter's export rides a
        different connection than the task dispatch, and a restarted GCS
        may still be waiting on the submitter's replay."""
        delay = 0.05
        for _ in range(6):
            try:
                data = self._worker.gcs.call(
                    "function_get", {"function_id": fid}, timeout=10)
            except Exception:
                data = None
            if data is not None:
                return data
            time.sleep(delay)
            delay = min(delay * 2, 0.5)
        if fallback_blob is not None:
            return fallback_blob
        raise RuntimeError(
            f"function {fid.hex()[:12]} not found in the GCS function table "
            f"(exporter gone and table not replayed?)")
