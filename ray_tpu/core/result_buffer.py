"""Executor-side batched result delivery (the return-path sibling of
`task_events.py`'s TaskEventBuffer).

Every finished task used to push its results to the owner as one
`report_task_result` notify, and the owner paid one `_obj_cv.notify_all()`
wakeup per task. Under a deep queue of small tasks the control plane
saturates on exactly that per-completion traffic (ENVELOPE_r05: 583
submits/s vs 81 completions/s). This buffer coalesces results PER OWNER:

- **Adaptive flush**: delivery runs on a dedicated flush thread. When no
  delivery is in flight, a reported result wakes the thread and ships
  immediately (one thread hop — single-task round-trip latency stays in
  the same regime, and the executor thread never blocks on the owner's
  socket). When results arrive WHILE a delivery is on the wire — the
  deep-queue regime, where completion rate exceeds delivery rate — they
  batch until the `result_buffer_flush_interval_ms` edge and one notify
  per owner carries all of them. The load signal is an actual in-flight
  delivery, not wall-clock spacing: a sequential caller's round-trips
  never wait out the interval.
- **No silent loss**: a flush whose owner link is down requeues the batch
  (ahead of anything buffered since, preserving completion order) and
  retries, bounded by `result_delivery_max_attempts` before the results
  are dropped with a warning — the same at-least-tried contract
  TaskEventBuffer's try_notify requeue gives task events. Self-scheduled
  retries back off exponentially with full jitter (util/backoff.py, base
  = the flush interval, cap = `result_retry_backoff_cap_ms`) instead of
  hammering a dead owner every interval; an explicit flush (new results,
  shutdown) still retries immediately, preserving per-owner order.

The owner side (`CoreWorker.rpc_report_task_result`) accepts the multi-task
`{"batch": [(task_id, results), ...]}` payload and collapses the per-task
condition-variable wakeups into one `notify_all` per batch.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Dict, List

from ray_tpu.core.config import get_config

logger = logging.getLogger(__name__)


class ResultBuffer:
    def __init__(self, worker):
        self._worker = worker
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # owner address -> [[task_id, results, attempts], ...] in completion
        # order (OrderedDict so flush delivers owners in first-result order)
        self._buffers: "OrderedDict[str, List[list]]" = OrderedDict()
        # owners backing off after failed deliveries:
        # owner -> [not_before_monotonic, [[task_id, results, attempts]...]]
        # — re-merged AHEAD of newer results at the next flush
        self._deferred: "OrderedDict[str, list]" = OrderedDict()
        # monotonic deadline of the scheduled flush; None = no flush claimed.
        # Also the immediate path's claim token: concurrent reporters that
        # see it non-None just append and ride the claimed flush.
        self._deadline = None
        self._last_flush = 0.0
        self._thread = None
        self._stopped = False
        self._inflight = 0  # deliveries between buffer-swap and wire
        # Serializes flush bodies (swap + deliver + requeue): without it a
        # concurrent flush (stop(), tests) could deliver an owner's NEWER
        # results while an older failed batch was still waiting to requeue,
        # breaking per-owner completion order.
        self._flush_mutex = threading.Lock()
        # instrumentation for tests/benchmarks
        self.flush_count = 0
        self.immediate_count = 0

    # ------------------------------------------------------------- reporting
    def report(self, owner: str, task_id, results) -> None:
        """Buffer one task's results for `owner`; the flush thread ships
        them ASAP when idle, interval-batched while a delivery is in
        flight."""
        interval = get_config().result_buffer_flush_interval_ms / 1000.0
        with self._lock:
            if not self._stopped and owner in self._deferred:
                # the owner is backing off after failed deliveries: join the
                # deferred batch so completion order holds when it re-merges
                self._deferred[owner][1].append([task_id, results, 0])
                self._ensure_thread_locked()
                self._cond.notify_all()
                return
            self._buffers.setdefault(owner, []).append([task_id, results, 0])
            if self._stopped:
                # after stop() no thread will ever drain a deferred flush:
                # drain synchronously (a concurrent flush makes this a no-op)
                drain = True
            else:
                drain = False
                if self._deadline is None:
                    if self._inflight > 0:
                        # a delivery is on the wire: results are arriving
                        # faster than they ship — batch to the interval edge
                        self._deadline = self._last_flush + interval
                    else:
                        # idle: ship as soon as the flush thread wakes
                        self._deadline = time.monotonic()
                        self.immediate_count += 1
                    self._ensure_thread_locked()
                    self._cond.notify_all()
                # else: a flush is already claimed; these results ride it
        if drain:
            self.flush()

    def flush(self) -> None:
        """Deliver everything buffered, one notify per owner."""
        with self._flush_mutex:
            with self._lock:
                # deferred batches re-merge AHEAD of anything buffered since
                # (per-owner completion order is the contract); any flush
                # retries them — the backoff only paces the SELF-scheduled
                # retry wakeups, never delays an explicit flush
                for owner, (_t, items) in list(self._deferred.items()):
                    self._buffers.setdefault(owner, [])[:0] = items
                self._deferred.clear()
                buffers, self._buffers = self._buffers, OrderedDict()
                self._deadline = None
                self._last_flush = time.monotonic()
                if buffers:
                    self._inflight += 1
            if not buffers:
                return
            try:
                for owner, items in buffers.items():
                    self._deliver(owner, items)
                self.flush_count += 1
            finally:
                with self._lock:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _deliver(self, owner: str, items: List[list]) -> None:
        w = self._worker
        payload = {"batch": [(tid, res) for tid, res, _ in items]}
        if getattr(w, "actor_id", None) is not None:
            # one process = one actor incarnation: stamp the batch so a
            # late delivery from a superseded instance (partition heal) is
            # rejected at the owner instead of resolving a pinned call
            payload["actor_incarnation"] = w._actor_incarnation
        try:
            w.peer(owner).notify("report_task_result", payload)
            return
        except Exception:
            with w._peers_lock:  # drop the stale cached connection
                w._peers.pop(owner, None)
        # Retry on a fresh SHORT-TIMEOUT connection: flushes for different
        # owners run sequentially, so a dead owner must not hold the shared
        # path for a full rpc_connect_timeout_s reconnect (close() still
        # flushes the kernel buffer, same one-shot idiom as raylet pushes).
        try:
            from ray_tpu.core import rpc

            cli = rpc.RpcClient(owner, connect_timeout=2)
            try:
                cli.notify("report_task_result", payload)
                return
            finally:
                cli.close()
        except Exception:
            pass
        # Owner unreachable right now: requeue AHEAD of anything buffered
        # since (completion order per owner is part of the contract), bounded
        # per item so a dead owner can't pin its batch forever.
        max_attempts = max(1, get_config().result_delivery_max_attempts)
        keep = []
        for tid, res, attempts in items:
            if attempts + 1 < max_attempts:
                keep.append([tid, res, attempts + 1])
            else:
                logger.warning(
                    "dropping results of task %s: owner %s unreachable "
                    "after %d delivery attempts", tid, owner, attempts + 1)
        if not keep:
            return
        cfg = get_config()
        from ray_tpu.util.backoff import ExponentialBackoff

        backoff = ExponentialBackoff(
            base_s=max(0.001, cfg.result_buffer_flush_interval_ms / 1000.0),
            cap_s=max(0.001, cfg.result_retry_backoff_cap_ms / 1000.0))
        with self._lock:
            if self._stopped:
                # the process is exiting; nothing will drain a requeue. The
                # raylet's recent-done failover (task_worker_died after the
                # retiring worker's grace window) is the owner's backstop.
                logger.warning(
                    "exiting with %d undeliverable task results for owner %s",
                    len(keep), owner)
                return
            # Defer with full-jitter backoff scaled by how often this batch
            # already failed: a down owner (e.g. mid head replacement) gets
            # progressively rarer self-scheduled retries instead of one per
            # flush interval.
            ent = self._deferred.get(owner)
            if ent is None:
                not_before = time.monotonic() + backoff.delay_for(keep[0][2])
                self._deferred[owner] = [not_before, keep]
            else:
                ent[1][:0] = keep
            self._ensure_thread_locked()
            self._cond.notify_all()

    # ------------------------------------------------------- deferred flusher
    def _ensure_thread_locked(self) -> None:
        """Caller holds _lock. Lazily start the deferred-flush thread (a
        process whose results always go out on the immediate path never
        spawns it)."""
        if self._thread is None or not self._thread.is_alive():
            t = threading.Thread(target=self._loop, name="result-buffer",
                                 daemon=True)
            self._thread = t
            t.start()

    def _loop(self) -> None:
        while True:
            due = False
            with self._lock:
                if self._stopped or self._worker._shutdown.is_set():
                    return
                nxt = self._deadline
                for not_before, _items in self._deferred.values():
                    nxt = not_before if nxt is None else min(nxt, not_before)
                if nxt is None:
                    self._cond.wait(timeout=5.0)
                else:
                    delay = nxt - time.monotonic()
                    if delay > 0:
                        self._cond.wait(timeout=delay)
                    else:
                        due = True
            if due:
                try:
                    self.flush()
                except Exception:
                    logger.debug("result flush failed", exc_info=True)

    def stop(self) -> None:
        """Final flush at shutdown/recycle: buffered results must never be
        lost to a clean exit (the owner would see the task hang until the
        raylet's worker-death notification failed it). Also WAITS for any
        delivery the loop thread has in flight — callers os._exit(0) right
        after stop(), which must not cut a swapped-out batch mid-wire."""
        with self._lock:
            self._stopped = True
            self._cond.notify_all()
        try:
            self.flush()
        except Exception:
            logger.debug("final result flush failed", exc_info=True)
        deadline = time.monotonic() + 5.0
        with self._lock:
            while self._inflight > 0 and time.monotonic() < deadline:
                self._cond.wait(timeout=0.1)
