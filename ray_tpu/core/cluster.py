"""In-process multi-node test cluster.

Equivalent of the reference's `ray.cluster_utils.Cluster`
(`python/ray/cluster_utils.py:99`, `add_node:165`, `remove_node:238`): run
multiple raylets on one machine so multi-node semantics — spillback, node
death, cross-node object transfer, placement groups — are testable without
real hosts. `remove_node` simulates node failure by hard-stopping the raylet
(its workers are killed), exercising the GCS health-check + actor-restart
paths.
"""

from __future__ import annotations

from typing import Dict, Optional

from ray_tpu.core.gcs import GcsServer, StandbyHead
from ray_tpu.core.raylet import Raylet


class Cluster:
    def __init__(self, gcs_snapshot_path: Optional[str] = None,
                 snapshot_uri: Optional[str] = None):
        """`snapshot_uri` selects the control-plane SnapshotStore
        ("file://<dir>" / "memory://<name>"); `gcs_snapshot_path` is the
        legacy file spelling. Either enables `restart_gcs()` (same
        address), `replace_head()` (NEW address) and the standby-head
        paths (`start_standby()` / `rolling_head_upgrade()`)."""
        self.gcs = GcsServer(snapshot_path=gcs_snapshot_path,
                             snapshot_uri=snapshot_uri)
        self.gcs.start()
        self._raylets: list[Raylet] = []
        self._standbys: list[StandbyHead] = []
        self.head: Optional[Raylet] = None

    @property
    def gcs_address(self) -> str:
        return self.gcs.address

    def add_node(
        self,
        num_cpus: float = 1,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        object_store_memory: Optional[int] = None,
    ) -> Raylet:
        r = dict(resources or {})
        r.setdefault("CPU", float(num_cpus))
        raylet = Raylet(
            gcs_address=self.gcs.address,
            resources=r,
            labels=labels,
            object_store_memory=object_store_memory,
        )
        raylet.start()
        self._raylets.append(raylet)
        if self.head is None:
            self.head = raylet
        return raylet

    def connect(self, **init_kwargs):
        """Connect the current process as a driver to this cluster."""
        import ray_tpu

        return ray_tpu.init(address=self.gcs.address, **init_kwargs)

    def restart_gcs(self) -> None:
        """Kill and restart the GCS on the SAME address (reference
        test_gcs_fault_tolerance.py pattern): raylets, drivers and actor
        workers detect the drop and re-register over their reconnecting
        clients, rebuilding live cluster state."""
        host, port = self.gcs.address.rsplit(":", 1)
        snapshot_uri = self.gcs._snapshot_uri
        self.gcs.stop()
        self.gcs = GcsServer(host=host, snapshot_uri=snapshot_uri,
                             port=int(port))
        self.gcs.start()

    def kill_head(self) -> None:
        """Crash-stop the GCS (no final snapshot flush, links just drop) —
        the failure a replacement head must recover from."""
        self.gcs.kill()

    def replace_head(self) -> str:
        """Start a REPLACEMENT GCS on a NEW address (control-plane HA): it
        restores node/actor/PG/KV state from the snapshot store, dials the
        snapshot-known raylets to announce its address, and the fleet
        (raylets, workers, drivers) re-registers over re-resolving
        reconnecting clients with backoff. Call `kill_head()` first to
        simulate the loss; returns the new GCS address."""
        host = self.gcs.address.rsplit(":", 1)[0]
        snapshot_uri = self.gcs._snapshot_uri
        if not snapshot_uri:
            raise ValueError("replace_head() needs a snapshot store "
                             "(pass snapshot_uri= to Cluster)")
        if not self.gcs._shutdown.is_set():
            self.gcs.kill()
        self.gcs = GcsServer(host=host, snapshot_uri=snapshot_uri, port=0)
        return self.gcs.start()

    def start_standby(self) -> StandbyHead:
        """Start a warm standby head tailing this cluster's snapshot store:
        it promotes itself (lease-epoch CAS) when the active head's lease
        expires or is relinquished. `adopt_promoted()` swaps it in as
        `self.gcs` once promoted."""
        uri = self.gcs._snapshot_uri
        if not uri:
            raise ValueError("standby head needs a snapshot store "
                             "(pass snapshot_uri= to Cluster)")
        standby = StandbyHead(uri, host=self.gcs.address.rsplit(":", 1)[0])
        standby.start()
        self._standbys.append(standby)
        return standby

    def adopt_promoted(self, standby: StandbyHead,
                       timeout: float = 60.0) -> str:
        """Wait for `standby` to promote and install it as this cluster's
        head. Returns the new GCS address."""
        promoted = standby.wait_promoted(timeout)
        if promoted is None:
            raise TimeoutError("standby did not promote within "
                               f"{timeout}s: {standby.stats()}")
        self.gcs = promoted
        return promoted.address

    def rolling_head_upgrade(self, timeout: float = 60.0) -> str:
        """Zero-downtime head upgrade: start a standby, DRAIN the active
        head's lease (expire it now, no TTL wait), let the standby promote
        via the epoch CAS and re-adopt the fleet, then retire the old head
        (no final flush — the store belongs to the new epoch). In-flight
        work rides worker/raylet links throughout; control-plane calls
        retry across the switchover. Returns the new GCS address."""
        old = self.gcs
        standby = self.start_standby()
        old._write_snapshot()  # hand over the freshest possible state
        old.drain_lease()
        address = self.adopt_promoted(standby, timeout)
        old.retire()
        return address

    def remove_node(self, raylet: Raylet) -> None:
        """Simulate node failure: kill raylet + its workers abruptly."""
        self._raylets.remove(raylet)
        if self.head is raylet:
            self.head = self._raylets[0] if self._raylets else None
        raylet.stop()
        # Tell GCS immediately instead of waiting for the health timeout so
        # tests are fast; the timeout path is tested separately.
        import ray_tpu.core.rpc as rpc

        try:
            c = rpc.connect_with_retry(self.gcs.address, timeout=5)
            c.call("drain_node", {"node_id": raylet.node_id.binary()})
            c.close()
        except Exception:
            pass

    def shutdown(self) -> None:
        import ray_tpu

        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
        for s in self._standbys:
            try:
                s.stop()
                # a promoted-but-never-adopted standby owns a live GcsServer
                if s.promoted is not None and s.promoted is not self.gcs:
                    s.promoted.stop()
            except Exception:
                pass
        self._standbys.clear()
        for r in self._raylets:
            try:
                r.stop()
            except Exception:
                pass
        self._raylets.clear()
        self.gcs.stop()
