"""Public API: init / remote / get / put / wait / actors / cluster info.

Mirrors the reference's `python/ray/_private/worker.py` public surface
(`ray.init:1115`, `get:2391`, `put:2538`, `wait:2600`, `get_actor:2722`,
`remote:2929`, `shutdown:1659`).
"""

from __future__ import annotations

import atexit
import os
import functools
import logging
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu.core.actor import ActorClass, ActorHandle
from ray_tpu.core.ids import ActorID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import SchedulingStrategy

logger = logging.getLogger(__name__)

_worker = None
_node = None
_init_lock = threading.RLock()


def _global_worker():
    if _worker is not None:
        return _worker
    # Inside a worker process the CoreWorker was created by worker_main.
    from ray_tpu.core.worker import current_worker

    w = current_worker()
    if w is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return w


def is_initialized() -> bool:
    if _worker is not None:
        return True
    from ray_tpu.core.worker import current_worker

    return current_worker() is not None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    object_store_memory: Optional[int] = None,
    ignore_reinit_error: bool = False,
    log_level: str = "WARNING",
    log_to_driver: bool = True,
) -> dict:
    """Start (or connect to) a cluster and connect this process as a driver.

    With no address, boots a head node in-process: GCS + raylet threads,
    worker subprocesses on demand (cf. reference `ray.init` local-cluster
    start, SURVEY §3.1). With `address="host:port"` (a GCS address),
    connects to an existing cluster as a driver only.
    """
    global _worker, _node
    with _init_lock:
        if _worker is not None:
            if ignore_reinit_error:
                return {"gcs_address": _worker.gcs_address}
            raise RuntimeError("ray_tpu.init() called twice; use ignore_reinit_error=True")

        logging.basicConfig(level=log_level)
        from ray_tpu.core.worker import CoreWorker, set_current_worker

        if address is not None and address.startswith("ray://"):
            # Remote-driver client mode (reference Ray Client,
            # python/ray/util/client/worker.py:81): a thin client over one
            # RPC connection; the real driver lives in the client server.
            ignored = {"num_cpus": num_cpus, "resources": resources,
                       "labels": labels,
                       "object_store_memory": object_store_memory}
            bad = [k for k, v in ignored.items() if v is not None]
            if bad:
                raise ValueError(
                    f"{bad} cannot be set in client mode — the cluster was "
                    f"configured where the client server runs")
            from ray_tpu.client import ClientWorker

            _worker = ClientWorker(address)
            atexit.register(shutdown)
            return {"gcs_address": _worker.gcs_address, "client": True}

        if address is None:
            # cluster-launcher integration (`ray_tpu exec/attach` export
            # this; reference RAY_ADDRESS): join instead of booting a head
            address = os.environ.get("RAY_TPU_ADDRESS") or None
            if address is not None and (num_cpus is not None or resources):
                logger.warning(
                    "RAY_TPU_ADDRESS=%s: joining the existing cluster; "
                    "init()'s num_cpus/resources apply only when booting a "
                    "local head and are ignored here", address)
        if address is None:
            from ray_tpu.core.node import HeadNode

            _node = HeadNode(
                num_cpus=num_cpus,
                resources=resources,
                labels=labels,
                object_store_memory=object_store_memory,
            )
            _node.start()
            gcs_address = _node.gcs_address
            raylet_address = _node.raylet_address
        else:
            gcs_address = address
            # find a raylet to attach to: ask GCS for nodes
            from ray_tpu.core import rpc as _rpc

            c = _rpc.connect_with_retry(gcs_address)
            nodes_ = c.call("get_all_nodes")
            c.close()
            alive = [n for n in nodes_ if n["alive"]]
            if not alive:
                raise ConnectionError("no alive nodes in cluster")
            raylet_address = alive[0]["address"]

        _worker = CoreWorker(
            mode="driver", raylet_address=raylet_address,
            gcs_address=gcs_address, log_to_driver=log_to_driver)
        set_current_worker(_worker)
        atexit.register(shutdown)
        return {"gcs_address": gcs_address, "raylet_address": raylet_address}


def shutdown() -> None:
    global _worker, _node
    with _init_lock:
        if _worker is not None:
            try:
                _worker.shutdown()
            except Exception:  # teardown: any half-open link may raise
                pass
            from ray_tpu.core.worker import set_current_worker

            set_current_worker(None)
            _worker = None
        if _node is not None:
            try:
                _node.stop()
            except Exception:
                pass
            _node = None
        try:
            atexit.unregister(shutdown)
        except Exception:
            pass


# ------------------------------------------------------------------ remote


class RemoteFunction:
    """Wrapper produced by `@remote` on a function
    (cf. reference `python/ray/remote_function.py:34`)."""

    def __init__(self, fn, options: Optional[dict] = None):
        self._fn = fn
        self._opts = dict(options or {})
        functools.update_wrapper(self, fn)

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._opts)
        merged.update(opts)
        return RemoteFunction(self._fn, merged)

    def remote(self, *args, **kwargs):
        w = _global_worker()
        o = self._opts
        resources = dict(o.get("resources") or {})
        if o.get("num_cpus") is not None:
            resources["CPU"] = float(o["num_cpus"])
        if o.get("num_tpus") is not None:
            resources["TPU"] = float(o["num_tpus"])
        if o.get("num_gpus") is not None:
            resources["GPU"] = float(o["num_gpus"])
        scheduling = o.get("scheduling_strategy")
        if scheduling is None:
            scheduling = SchedulingStrategy(name=o.get("scheduling", "DEFAULT"))
            pg = o.get("placement_group")
            if pg is not None:
                scheduling.placement_group_id = pg.id
                scheduling.bundle_index = o.get("placement_group_bundle_index", -1)
        num_returns = o.get("num_returns", 1)
        if num_returns in ("dynamic", "streaming"):
            num_returns = -1  # generator task (reference num_returns="dynamic")
        refs = w.submit_task(
            self._fn, args, kwargs,
            num_returns=num_returns,
            resources=resources,
            scheduling=scheduling,
            max_retries=o.get("max_retries", 0),
            retry_exceptions=o.get("retry_exceptions", False),
            runtime_env=o.get("runtime_env"),
            max_calls=int(o.get("max_calls") or 0),
        )
        if num_returns == -1:
            return w.make_dynamic_generator(refs[0])
        return refs[0] if num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote functions cannot be called directly; use "
            f"`{self._fn.__name__}.remote(...)`.")


def remote(*args, **kwargs):
    """`@remote` decorator for functions and classes, with or without options."""
    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        target = args[0]
        if isinstance(target, type):
            return ActorClass(target)
        return RemoteFunction(target)

    def decorator(target):
        if isinstance(target, type):
            return ActorClass(target, kwargs)
        return RemoteFunction(target, kwargs)

    return decorator


def method(**opts):
    """Per-method options decorator (parity shim; options resolved call-side)."""

    def decorator(fn):
        fn._ray_tpu_method_opts = opts
        return fn

    return decorator


# ------------------------------------------------------------------ objects


def put(value: Any) -> ObjectRef:
    return _global_worker().put(value)


def push(ref: ObjectRef, node_ids=None) -> int:
    """Proactively broadcast an owned plasma object to other nodes' object
    stores (reference PushManager semantics, push_manager.h:29): downstream
    consumers then read a local copy instead of serializing on one source.
    Returns the number of nodes the push was dispatched to."""
    return _global_worker().push_object(ref, node_ids)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: Optional[float] = None):
    from ray_tpu.core.object_ref import ObjectRefGenerator

    if isinstance(refs, ObjectRefGenerator):
        raise TypeError(
            "got an ObjectRefGenerator (num_returns='dynamic' task); iterate "
            "it for item refs — e.g. [ray_tpu.get(r) for r in gen]")
    w = _global_worker()
    if isinstance(refs, ObjectRef):
        return w.get([refs], timeout=timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects an ObjectRef or list, got {type(refs)}")
    return w.get(list(refs), timeout=timeout)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    return _global_worker().wait(list(refs), num_returns, timeout, fetch_local)


# ------------------------------------------------------------------ actors


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    info = _global_worker().get_actor_info(name=name, namespace=namespace)
    if info is None or info["state"] == "DEAD":
        raise ValueError(f"no live actor named '{name}'")
    return ActorHandle(info["actor_id"], info.get("class_name", ""))


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    _global_worker().kill_actor(actor.actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False,
           recursive: bool = False) -> None:
    """Cancel the task that produces `ref` (reference `ray.cancel`).

    Best-effort on the work, hard guarantee on the ref: once the owner
    claims the cancel, `get(ref)` resolves to `TaskCancelledError` — never
    hangs — whether the task was still queued (raylet dequeue), running
    (cooperative exception injection at the next bytecode boundary), or a
    queued actor call (purged from the actor's mailbox). A task that
    already completed keeps its value. `force=True` escalates a running
    task to SIGKILL of its worker (non-retryable); `recursive=True` walks
    each owner's child-task table (parent_task_id lineage) so the whole
    tree dies leaf-ward with no orphaned grandchildren."""
    w = _global_worker()
    w.cancel(ref, force=force, recursive=recursive)


# ------------------------------------------------------------------ cluster


def nodes() -> List[dict]:
    return _global_worker().gcs.call("get_all_nodes")


def cluster_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes():
        if n["alive"]:
            for r, q in n["resources_total"].items():
                total[r] = total.get(r, 0.0) + q
    return total


def available_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes():
        if n["alive"]:
            for r, q in n["resources_available"].items():
                total[r] = total.get(r, 0.0) + q
    return total


class RuntimeContext:
    def __init__(self, worker):
        self._w = worker

    @property
    def job_id(self):
        return self._w.job_id

    @property
    def node_id(self):
        return self._w.node_id

    @property
    def worker_id(self):
        return self._w.worker_id

    @property
    def actor_id(self):
        return self._w.actor_id

    @property
    def gcs_address(self):
        return self._w.gcs_address

    @property
    def placement_group_id(self):
        return getattr(self._w, "placement_group_id", None)

    def get(self):
        return {
            "job_id": self.job_id,
            "node_id": self.node_id,
            "worker_id": self.worker_id,
            "actor_id": self.actor_id,
            "placement_group_id": self.placement_group_id,
        }


def get_runtime_context() -> RuntimeContext:
    from ray_tpu.core.worker import current_worker

    w = current_worker() or _global_worker()
    return RuntimeContext(w)


def get_gpu_ids() -> List[int]:
    """Reference `ray.get_gpu_ids`. This framework targets TPU hosts —
    there are never CUDA devices to enumerate; the accelerator analog is
    `get_tpu_ids()`."""
    return []


def get_tpu_ids() -> List[int]:
    """Chip indices the raylet granted the current task or actor (the
    TPU-native `ray.get_gpu_ids`): DISJOINT across concurrent tasks on a
    node — whole chips for integer demands, a shared chip index for
    fractional ones. [] when nothing is reserved."""
    from ray_tpu.core.worker import current_worker

    w = current_worker() or _global_worker()
    ids = getattr(getattr(w, "_tls", None), "tpu_ids", None)
    if ids is None:
        ids = list(getattr(w, "_actor_tpu_ids", []) or [])
    return list(ids)


def timeline() -> List[dict]:
    """Cluster-wide chrome-trace events: this process's spans plus the
    worker spans aggregated in the GCS (reference `ray.timeline()`,
    _private/state.py:851)."""
    from ray_tpu.util.tracing import get_events

    events = get_events()
    try:
        w = _global_worker()
        w.flush_profile_events()
        remote = w.gcs.call("get_profile_events", timeout=10)
        # dedupe by origin worker id (pids collide across hosts)
        local_src = w.worker_id.binary().hex()
        events = events + [e for e in remote if e.get("_src") != local_src]
    except Exception:
        pass
    return events
