"""GCS: the cluster control plane.

Equivalent of the reference's GCS server (`src/ray/gcs/gcs_server/
gcs_server.h:77`): node membership + health (GcsNodeManager,
GcsHealthCheckManager), actor lifecycle with restart-on-failure
(GcsActorManager `gcs_actor_manager.h:281`), placement groups with 2-phase
reserve/commit (GcsPlacementGroupManager `gcs_placement_group_manager.h:223`),
jobs, internal KV, pubsub fan-out, and the cluster resource view that backs
scheduling (GcsResourceManager). Storage is in-memory (the reference's
default `InMemoryStoreClient`, `gcs_table_storage.h:354`); a persistence
hook can be added behind the same table interface.
"""

from __future__ import annotations

import os
import logging
import socket
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from ray_tpu.core import rpc
from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ActorID, JobID, NodeID, PlacementGroupID
from ray_tpu.core.scheduler import SchedulingPolicy, NodeView
from ray_tpu.core.task_spec import ActorCreationSpec, ActorInfo, ActorState

logger = logging.getLogger(__name__)

# Pubsub channels (cf. reference src/ray/protobuf/pubsub.proto:28-46)
CH_NODES = "nodes"
CH_ACTORS = "actors"
CH_RESOURCES = "resources"
CH_ERRORS = "errors"
CH_CONTROL = "control"  # cluster-wide commands (global_gc, ...)
CH_LOGS = "logs"        # worker stdout/stderr fan-out to drivers


def _head_metrics() -> dict:
    """Lazy HA metric handles (util/metrics.py): shared names across the
    active head, a promoted standby and the raylet-side announce drops."""
    from ray_tpu.util.metrics import get_or_create

    return {
        "failovers": get_or_create(
            "counter", "ray_tpu_head_failovers_total",
            "standby head promotions"),
        "promotion_s": get_or_create(
            "gauge", "ray_tpu_head_promotion_seconds",
            "lease-expiry -> first-scheduled-task latency of the last "
            "promotion"),
        "fencing": get_or_create(
            "counter", "ray_tpu_fencing_rejections_total",
            "stale-head writes/announces rejected by the fencing epoch",
            tag_keys=("site",)),
    }


def _node_metrics() -> dict:
    """Node-failure-domain metric handles: shared names between the GCS
    (which declares deaths and ingests warm-lease joins) and the autoscaler
    (which counts relaunches)."""
    from ray_tpu.util.metrics import get_or_create

    return {
        "deaths": get_or_create(
            "counter", "ray_tpu_node_deaths_total",
            "nodes declared dead", tag_keys=("reason",)),
        "relaunches": get_or_create(
            "counter", "ray_tpu_node_relaunches_total",
            "autoscaler replacements launched for dead nodes"),
        "join_warm": get_or_create(
            "gauge", "ray_tpu_node_join_warm_lease_seconds",
            "node join -> first warm (forked) lease latency of the most "
            "recent joiner"),
        # --- partition failure domain (incarnation fencing + quarantine) ---
        "fenced": get_or_create(
            "counter", "ray_tpu_node_fenced_total",
            "nodes told to fence (stale incarnation after a partition "
            "heal): the zombie kills its workers and rejoins fresh"),
        "quarantines": get_or_create(
            "counter", "ray_tpu_node_quarantines_total",
            "nodes quarantined for degraded heartbeat delivery (no new "
            "dispatch, autoscaler holds replacement)"),
        "stale_rejections": get_or_create(
            "counter", "ray_tpu_stale_incarnation_rejections_total",
            "messages rejected for carrying a superseded node/actor "
            "incarnation", tag_keys=("site",)),
    }


def _job_metrics() -> dict:
    """Job failure-domain metric handles: driver-death fate-sharing reaps
    declared by the GCS (conn-close fast path, probe backstop, or
    post-failover snapshot probe)."""
    from ray_tpu.util.metrics import get_or_create

    return {
        "reaps": get_or_create(
            "counter", "ray_tpu_job_reaps_total",
            "dead jobs reaped (driver-death fate-sharing): non-detached "
            "actors killed, tasks cancelled, leases and demand released, "
            "owned objects dropped, function exports freed"),
    }


class GcsServer:
    def __init__(self, host: str = "127.0.0.1",
                 snapshot_path: Optional[str] = None,
                 snapshot_interval_s: float = 5.0,
                 port: int = 0,
                 snapshot_uri: Optional[str] = None,
                 preloaded_snapshot: Optional[bytes] = None,
                 lease_grant: Optional[dict] = None):
        """Control-plane persistence rides a pluggable `SnapshotStore`
        (snapshot_store.py — the role Redis plays for the reference's HA
        GCS, `gcs_table_storage.h`): the durable tables (internal KV, jobs,
        function table, actor metadata, node table, placement groups)
        serialize into versioned, checksummed, atomically-swapped blobs
        selected by `snapshot_uri` ("file://<dir>" or "memory://<name>";
        `snapshot_path` is the legacy spelling of a file store; config
        `gcs_snapshot_uri` is the env-driven default). A restarted head on
        the SAME address rebuilds live state from re-registrations alone; a
        REPLACEMENT head on a new address additionally restores the node
        and PG tables from the snapshot, dials the snapshot-known raylets
        to announce its address, and re-adopts them as they re-register
        (see _readopt_loop). Actor liveness still comes only from worker
        re-registration — the snapshot restores identity and restart
        budgets, never liveness."""
        self._server = rpc.RpcServer(host, port)
        self._server.register_all(self)
        self._lock = threading.RLock()
        from ray_tpu.core.snapshot_store import VersionedSnapshots, \
            store_from_uri

        uri = snapshot_uri or (get_config().gcs_snapshot_uri or None)
        if uri is None and snapshot_path:
            uri = f"file://{self._migrate_legacy_snapshot(snapshot_path)}"
        self._snapshot_uri = uri
        self._snapshots: Optional[VersionedSnapshots] = None
        if uri:
            self._snapshots = VersionedSnapshots(
                store_from_uri(uri), prefix="gcs",
                keep=get_config().gcs_snapshot_keep)
        self._snapshot_interval_s = snapshot_interval_s
        self._dirty = False
        self._snapshot_write_lock = threading.Lock()
        self._snapshots_written = 0
        self._snapshot_last_version = 0

        # --- lease / fencing (head_lease.py): the active head renews a TTL
        # lease stored beside the snapshots; the lease EPOCH is the fencing
        # token every durable write and raylet-facing announce carries. A
        # head whose epoch trails the store's is FENCED: its snapshot saves
        # raise, its announces are dropped by raylets, and on_fenced fires
        # (node_main exits there; tests assert on it).
        import uuid as _uuid

        from ray_tpu.core.head_lease import HeadLease

        self.session_id: str = _uuid.uuid4().hex[:16]
        self._restored_fence_epoch = 0  # epoch floor carried by the snapshot
        self._preloaded_snapshot = preloaded_snapshot
        self._lease: Optional[HeadLease] = None
        self._lease_owner: str = ""
        self._lease_draining = False
        self.fence_epoch: int = 0
        self._fenced = threading.Event()
        self._fencing_rejections = 0
        self.on_fenced = None  # callback: a newer head took over
        # set by a promoting StandbyHead: lease-expiry/promotion timestamps;
        # first_schedule_at lands when this head first dispatches work
        self.promotion: Optional[dict] = None
        if self._snapshots is not None:
            self._lease = HeadLease(self._snapshots.store)
            if lease_grant is not None:
                # a StandbyHead already won the acquire CAS for us
                self._lease_owner = lease_grant["owner"]
                self.fence_epoch = lease_grant["epoch"]
                self.promotion = {
                    "epoch": self.fence_epoch,
                    "lease_expired_at": lease_grant.get("lease_expired_at"),
                    "promoted_at": None,
                    "first_schedule_at": None,
                    "tailed_version": lease_grant.get("tailed_version"),
                }
            else:
                from ray_tpu.core.head_lease import new_owner_token

                self._lease_owner = new_owner_token()

        # --- delta-encoded resource fan-out state: per-publish sequence,
        # the set of nodes whose view changed since the last publish, and
        # a full-snapshot latch (topology change / new subscriber / first
        # publish). Guarded by self._lock.
        self._bcast_seq = 0
        self._bcast_dirty: set = set()        # node hexids changed
        self._bcast_removed: set = set()      # node hexids removed
        self._bcast_full_needed = True
        self._bcast_fulls = 0
        self._bcast_deltas = 0
        self._bcast_bytes = 0                 # payload bytes x subscribers
        # 2-phase PG creations serialize here: a client retry racing the
        # restored head's resume of the same (idempotent) creation must not
        # run two concurrent placements and leak the loser's reservations
        self._pg_2pc_lock = threading.Lock()
        self._pg_retry_active = False  # one paced PENDING-retry pass at a time
        # nodes restored from the snapshot, awaiting raylet re-registration
        # (address -> node_id); the readopt loop dials them to announce the
        # new head address, and the health loop reaps silent ones
        self._restored_nodes: Dict[str, bytes] = {}

        # --- node failure domain (autoscaler-driven replacement + warm
        # onboarding) ---
        # hot runtime-env keys: env keys with recent lease traffic, fed by
        # raylet heartbeats and shipped in the register_node reply so a
        # JOINING raylet pre-spawns fork templates for them (warm node
        # onboarding). key -> {"runtime_env": ..., "last_seen": monotonic}.
        self._hot_envs: Dict[Optional[str], dict] = {}
        # death accounting (ray_tpu_node_deaths_total{reason=}); graceful
        # drains are tallied apart — scale-down is not failure
        self._node_deaths: Dict[str, int] = {}
        self._node_drains = 0
        # the autoscaler's own reconcile counters, reported each tick via
        # rpc_autoscaler_report so gcs_stats is the one observability stop
        self._autoscaler_stats: dict = {}
        # node-join -> first-warm-lease samples reported by joining raylets
        from collections import deque as _deque

        self._warm_lease_joins: "_deque" = _deque(maxlen=100)
        # actors whose restart found no capacity RIGHT NOW (their node died
        # and the replacement has not joined yet): actor_id -> next retry
        # monotonic. The health loop re-runs scheduling paced; a node
        # registration makes every entry immediately due.
        self._pending_restarts: Dict[ActorID, float] = {}
        # first time each actor was parked (bounds the total wait: past
        # actor_restart_pending_timeout_s the restart is declared DEAD)
        self._pending_restart_since: Dict[ActorID, float] = {}
        self._restart_retry_active = False
        self._bundle_resched_active = False
        # debounced resource fan-out (completion-path fast lane): at most
        # one CH_RESOURCES publish per resource_broadcast_period_ms
        from ray_tpu.util.debounce import Debouncer

        self._bcast_debounce = Debouncer(
            self._publish_resources,
            lambda: get_config().resource_broadcast_period_ms / 1000.0,
            skip_deferred=lambda: self._shutdown.is_set())

        # node table: node_id(bytes) -> info dict
        self._nodes: Dict[bytes, dict] = {}
        self._raylet_clients: Dict[bytes, rpc.RpcClient] = {}
        self._last_heartbeat: Dict[bytes, float] = {}

        # --- partition failure domain: incarnation fencing + quarantine ---
        # per-node-IDENTITY incarnation: monotonically increasing, stamped
        # at registration, snapshot-persisted. Declaring a node dead
        # INVALIDATES its identity (added to _dead_node_ids): a zombie that
        # comes back after a partition heal gets a typed fence reply on its
        # next heartbeat/register — it must kill its workers (they host
        # actor incarnations that were restarted elsewhere) and rejoin as a
        # fresh node. (Reference: Ray's fault model treats asymmetric
        # reachability as first-class; the incarnation is the fencing token
        # at node granularity, like the head-lease epoch at head
        # granularity.)
        self._node_incarnations: Dict[bytes, int] = {}
        # invalidated identities, INSERTION-ORDERED so the bound evicts the
        # oldest and the snapshot persists the newest (a dict used as an
        # ordered set: values unused)
        self._dead_node_ids: Dict[bytes, None] = {}
        self._node_fences = 0
        # gray-failure quarantine: degraded-heartbeat nodes are quarantined
        # (no new leases/dispatch; the autoscaler holds its replacement)
        # BEFORE the death bound and rejoin without replacement on recovery
        self._node_quarantines = 0
        self._quarantine_recoveries = 0
        # stale-incarnation rejections by site (heartbeat/register/
        # reregister_actor/actor_creation_done/actor_failed)
        self._stale_rejections: Dict[str, int] = {}

        # kv: namespace -> key -> value
        self._kv: Dict[str, Dict[bytes, Any]] = {}

        # function table: content-addressed export-once function/class
        # pickles (reference function_manager.py export path). Durable via
        # the snapshot: actor restart-on-failure resolves class blobs here.
        # Insertion-ordered for FIFO eviction at function_table_max_bytes.
        self._functions: Dict[bytes, bytes] = {}
        self._function_bytes = 0
        self._function_puts = 0  # put RPCs since boot (export-once proof)
        self._function_evictions = 0

        # recent worker log lines for `ray_tpu logs`
        from collections import deque

        self._recent_logs = deque(maxlen=1000)

        # actors
        self._actors: Dict[ActorID, ActorInfo] = {}
        self._actor_specs: Dict[ActorID, ActorCreationSpec] = {}
        self._actor_owners: Dict[ActorID, str] = {}
        self._named_actors: Dict[tuple, ActorID] = {}  # (namespace, name) -> id

        # actors restored from a snapshot, awaiting worker re-registration
        self._awaiting_rereg: Dict[ActorID, float] = {}

        # placement groups
        self._pgs: Dict[PlacementGroupID, dict] = {}

        # jobs
        self._jobs: Dict[bytes, dict] = {}
        # --- job failure domain (driver-death fate-sharing) ---
        # live driver conn IDENTITY per job: the conn-close hook only reaps
        # if ITS conn is still the registered one — a reconnecting driver
        # re-registers on a new conn first, and the old conn's late close
        # must not reap the live job
        self._job_conns: Dict[bytes, int] = {}
        # probe backstop: RUNNING jobs with no live conn (close hook lost,
        # or restored from a snapshot after failover) get their
        # driver_address probed once this monotonic deadline passes
        self._job_probe_after: Dict[bytes, float] = {}
        # snapshot-restored jobs flipped RUNNING->FAILED that still need a
        # probe-then-reap (a surviving driver re-registers and escapes)
        self._restored_unreaped: Dict[bytes, None] = {}
        # function exports by owning job: an export is freed at reap only
        # when the dead job was its LAST owner (shared content-addressed
        # blobs survive)
        self._function_jobs: Dict[bytes, set] = {}
        self._job_reap_stats: Dict[str, int] = {
            "jobs_reaped": 0, "actors_killed": 0, "detached_spared": 0,
            "queued_cancelled": 0, "workers_killed": 0,
            "objects_dropped": 0, "bytes_dropped": 0, "functions_freed": 0}

        # task events: ring buffer of recent task lifecycle records
        # (reference GcsTaskManager + per-worker TaskEventBuffer,
        # src/ray/core_worker/task_event_buffer.h)
        self._task_events: Dict[bytes, dict] = {}
        self._task_events_order: List[bytes] = []
        self._task_events_dropped = 0  # evictions since boot (truncation flag)
        self._max_task_events = 10000
        self._task_counts = {"submitted": 0, "finished": 0, "failed": 0}
        self._profile_events: List[dict] = []

        # distributed tracing (observability plane): spans carrying a
        # trace_id index into a bounded ring of traces (oldest trace
        # evicted whole); per-source clock offsets from worker clock
        # probes align the merged timeline; per-stage latencies feed the
        # p50/p99 roll-up in gcs_stats
        self._traces: "OrderedDict[str, List[dict]]" = OrderedDict()
        self._traces_evicted = 0
        self._spans_dropped = 0       # worker-side ring overflow, summed
        self._span_clock_offsets: Dict[str, float] = {}  # src -> offset_us
        self._stage_lat_us: Dict[str, List[float]] = {}

        # pubsub: channel -> list[ServerConnection]
        self._subs: Dict[str, List[rpc.ServerConnection]] = {}

        self._policy = SchedulingPolicy()
        self._shutdown = threading.Event()
        self._health_thread: Optional[threading.Thread] = None

    @staticmethod
    def _migrate_legacy_snapshot(snapshot_path: str) -> str:
        """Legacy `snapshot_path` pointed at a single pickle FILE; the
        store needs a directory. If an old-format file exists there, root
        the store beside it (`<path>.d`) and import the pickle as version
        1 — a pre-HA head's snapshot still restores after an upgrade.
        Returns the directory to root the FileSnapshotStore on."""
        if not os.path.isfile(snapshot_path):
            return snapshot_path
        from ray_tpu.core.snapshot_store import FileSnapshotStore, \
            VersionedSnapshots

        root = snapshot_path + ".d"
        try:
            store = FileSnapshotStore(root)
            if not store.list_keys(prefix="gcs-"):
                with open(snapshot_path, "rb") as f:
                    legacy = f.read()
                VersionedSnapshots(store, prefix="gcs").save(legacy)
                logger.info("migrated legacy GCS snapshot %s into store %s",
                            snapshot_path, root)
        except Exception:
            logger.exception("legacy snapshot migration failed; starting "
                             "from the store at %s", root)
        return root

    # ------------------------------------------------------------------ boot
    def start(self) -> str:
        self._load_snapshot()
        if self._lease is not None and self.fence_epoch == 0:
            # operator-started head: force-take the lease (epoch bump). Any
            # previous holder — a head this one replaces — is fenced from
            # this point; only a StandbyHead waits out the TTL instead.
            # The snapshot's persisted fence_epoch floors the new epoch: a
            # torn/lost lease RECORD must not reset the epoch below one the
            # fleet already adopted (that would invert every fencing check).
            self.fence_epoch = self._lease.acquire(
                self._lease_owner, force=True, settle_s=0,
                floor=self._restored_fence_epoch + 1)
        self._server.start()
        if self._lease is not None:
            # partition sidedness for the lease_renew fault point: a net
            # split that cuts this head from the store's side starves its
            # renewals (head-in-minority composes PR 11's lease fencing)
            self._lease.origin = self._server.address
        if self.promotion is not None:
            self.promotion["promoted_at"] = time.time()
        self._write_address_file()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="gcs-health", daemon=True
        )
        self._health_thread.start()
        if self._snapshots is not None:
            threading.Thread(target=self._snapshot_loop, name="gcs-snapshot",
                             daemon=True).start()
        if self._lease is not None:
            threading.Thread(target=self._lease_loop, name="gcs-lease",
                             daemon=True).start()
        if self._restored_nodes or any(
                p.get("state") == "PREPARING" for p in self._pgs.values()):
            threading.Thread(target=self._readopt_loop, name="gcs-readopt",
                             daemon=True).start()
        logger.info("GCS listening on %s (session %s epoch %d)",
                    self._server.address, self.session_id, self.fence_epoch)
        return self._server.address

    def _write_address_file(self) -> None:
        """Publish this head's address for re-resolution (config
        gcs_address_file): raylets/workers/drivers re-read the file on
        every reconnect attempt, so a replacement head on a new address is
        found without restarting anything. Atomic swap through a tmp file
        unique per WRITER (pid + thread + object id — an old and a new head
        in one process must not stomp each other's tmp) and fsynced before
        the rename — a reader never sees a half-written or empty address,
        and `read_gcs_address_file` treats an empty read as "no answer"
        (retry), never as an address."""
        path = get_config().gcs_address_file
        if not path:
            return
        try:
            tmp = (f"{path}.tmp{os.getpid()}."
                   f"{threading.get_ident()}.{id(self)}")
            with open(tmp, "w") as f:
                f.write(self._server.address)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            logger.exception("could not write GCS address file %s", path)

    # ------------------------------------------------------- lease / fencing
    def _lease_loop(self) -> None:
        """Renew the head lease every ttl/3. A renewal WRITE lost to the
        injected `lease_renew` fault (or a store blip) just shortens the
        runway — the lease expires and a standby takes over; a renewal that
        READS a bumped epoch means that already happened: fence ourselves."""
        from ray_tpu.core.head_lease import LeaseLostError

        cfg = get_config()
        period = cfg.head_lease_renew_period_s or (self._lease.ttl_s / 3.0)
        while not self._shutdown.wait(period):
            if self._fenced.is_set():
                return
            try:
                if self._lease_draining:
                    # rolling upgrade: no renewals (we relinquished), but
                    # keep READING so the successor's epoch bump fences —
                    # and thereby retires — this head automatically
                    self._lease.check(self.fence_epoch)
                    continue
                self._lease.renew(self._lease_owner, self.fence_epoch,
                                  address=self._server.address,
                                  snapshot_version=self._snapshot_last_version)
            except LeaseLostError as e:
                self._note_fenced(f"lease renewal: {e}")
                return
            except rpc.RpcDisconnected as e:
                logger.warning("head lease renewal lost (%s); lease expires "
                               "unless a later renewal lands", e)
            except Exception:
                logger.exception("head lease renewal failed")

    def _note_fenced(self, reason: str) -> None:
        if self._fenced.is_set():
            return
        self._fenced.set()
        logger.warning("GCS %s FENCED (epoch %d): %s — retiring",
                       self._server.address, self.fence_epoch, reason)
        cb = self.on_fenced
        if cb is not None:
            try:
                cb()
            except Exception:
                logger.exception("on_fenced callback failed")
        # A fenced head must stop SERVING, not just stop writing: still-
        # connected clients would otherwise keep reading (and mutating) a
        # dead epoch's view — e.g. its health loop declaring the departed
        # fleet dead and publishing actor deaths to subscribed drivers.
        # Dropping the connections makes every client re-resolve (via
        # address file / raylet answerback) to the head that fenced us.
        threading.Thread(target=self._retire_after_fence,
                         name="gcs-fenced-retire", daemon=True).start()

    def _retire_after_fence(self) -> None:
        time.sleep(0.05)  # let in-flight replies (incl. our rejection) flush
        if not self._shutdown.is_set():
            self.retire()

    def rpc_head_fenced(self, conn, req_id, payload):
        """A successor head telling us it bumped the lease epoch (the
        promoted standby dials the address the old lease record carried).
        Shrinks the stale-serving window from a lease-read period to one
        RPC; epoch-checked so a confused caller can't fence the real
        head."""
        if int(payload.get("epoch", 0)) > self.fence_epoch:
            self._note_fenced(
                f"successor at {payload.get('address')} announced epoch "
                f"{payload.get('epoch')}")
            return True
        return False

    def _reject_fenced_write(self, site: str) -> None:
        self._fencing_rejections += 1
        try:
            _head_metrics()["fencing"].inc(tags={"site": site})
        except Exception:
            pass
        self._note_fenced(f"write rejected at {site}")

    def drain_lease(self) -> None:
        """Rolling head upgrade, step 1: stop renewing and expire the lease
        NOW so a standby promotes immediately (no TTL wait). This head keeps
        serving reads until the standby's epoch bump fences it; call
        `retire()` once the standby is active."""
        if self._lease is None:
            raise RuntimeError("no snapshot store — no lease to drain")
        self._lease_draining = True
        self._lease.relinquish(self._lease_owner, self.fence_epoch)
        logger.info("GCS %s relinquished head lease (epoch %d) for rolling "
                    "upgrade", self._server.address, self.fence_epoch)

    def retire(self) -> None:
        """Rolling head upgrade, step 3: the standby is active; stop without
        fighting it for the store (no final snapshot flush)."""
        self._fenced.set()
        self._shutdown.set()
        for c in self._raylet_clients.values():
            c.close()
        self._server.stop()

    def _note_first_schedule(self) -> None:
        """Stamp a promoted head's first dispatched work: the far edge of
        the tracked promotion latency (lease-expiry -> first-scheduled-task,
        HEADFAIL artifact + ray_tpu_head_promotion_seconds)."""
        p = self.promotion
        if p is None or p.get("first_schedule_at") is not None:
            return
        p["first_schedule_at"] = time.time()
        expired = p.get("lease_expired_at")
        if expired is not None:
            p["latency_s"] = p["first_schedule_at"] - expired
            try:
                _head_metrics()["promotion_s"].set(p["latency_s"])
            except Exception:
                pass

    # ------------------------------------------------------- persistence
    def _load_snapshot(self) -> None:
        if self._snapshots is None:
            return
        import pickle

        try:
            if self._preloaded_snapshot is not None:
                # a promoting StandbyHead hands over its tailed payload:
                # restore is a deserialize, not a store walk (warm takeover)
                payload = self._preloaded_snapshot
            else:
                payload = self._snapshots.load_latest()
            if payload is None:
                return
            data = pickle.loads(payload)
            with self._lock:
                # the cluster session survives head changes: raylets use it
                # as the fingerprint for one-RPC re-adoption; the persisted
                # fence_epoch floors any later lease acquire (a torn lease
                # record must not reset the epoch under the fleet)
                self.session_id = data.get("session_id", self.session_id)
                self._restored_fence_epoch = int(data.get("fence_epoch", 0))
                self._kv = data.get("kv", {})
                self._functions = data.get("functions", {})
                self._function_bytes = sum(
                    len(b) for b in self._functions.values())
                for jid, job in data.get("jobs", {}).items():
                    job = dict(job)
                    if job.get("status") == "RUNNING":
                        # its driver may have died with the old head;
                        # nothing will ever mark it finished. But a
                        # SURVIVING driver re-registers (replay) and
                        # revives the entry — so flip it FAILED now and
                        # only REAP after the health loop's probe finds
                        # its driver_address actually dead.
                        job["status"] = "FAILED"
                        job.setdefault("end_time", time.time())
                        self._restored_unreaped[jid] = None
                    self._jobs[jid] = job
                # Actors come back as awaiting-re-registration: their budget
                # and identity restore from the snapshot, liveness only from
                # the worker's reregister_actor (the source of truth). The
                # health loop reaps those that never re-announce.
                for aid, m in data.get("actor_meta", {}).items():
                    info = ActorInfo(
                        actor_id=aid, name=m["name"], namespace=m["namespace"],
                        state=ActorState.RESTARTING,
                        max_restarts=m["max_restarts"],
                        num_restarts=m["num_restarts"],
                        class_name=m.get("class_name", ""),
                    )
                    self._actors[aid] = info
                    self._actor_owners[aid] = m.get("owner", "")
                    if m.get("spec") is not None:
                        self._actor_specs[aid] = m["spec"]
                    if m["name"]:
                        self._named_actors[(m["namespace"], m["name"])] = aid
                    self._awaiting_rereg[aid] = time.monotonic()
                # Node table: restored entries let a REPLACEMENT head (new
                # address) know which raylets exist and where, so it can
                # dial them and announce itself (_readopt_loop). They stay
                # provisional ("restored") until the raylet re-registers;
                # the heartbeat timeout reaps ones that never do.
                now = time.monotonic()
                for nid, n in data.get("nodes", {}).items():
                    n = dict(n)
                    n["alive"] = True
                    n["restored"] = True
                    self._nodes[nid] = n
                    self._last_heartbeat[nid] = now
                    self._restored_nodes[n["address"]] = nid
                # fencing state: per-identity incarnation counters and the
                # invalidated (dead) identities survive a head change, so
                # a partition-era zombie can't slip past a fresh head
                for nid, inc in data.get("node_incarnations", {}).items():
                    self._node_incarnations[nid] = max(
                        self._node_incarnations.get(nid, 0), int(inc))
                for nid in data.get("dead_nodes", ()):
                    self._dead_node_ids[nid] = None
                nfc = data.get("node_failure_counters")
                if nfc:
                    self._node_deaths.update(nfc.get("deaths", {}))
                    self._node_drains += int(nfc.get("drains", 0))
                    self._node_fences += int(nfc.get("fences", 0))
                    self._node_quarantines += int(
                        nfc.get("quarantines", 0))
                    self._quarantine_recoveries += int(
                        nfc.get("quarantine_recoveries", 0))
                    self._stale_rejections.update(
                        nfc.get("stale_rejections", {}))
                # Placement groups: bundle reservations live on in the
                # raylets (which survived the head), so the restored table
                # — bundles, strategy, bundle->node placement — makes PG
                # state consistent again the moment nodes re-register. A
                # creation the old head died inside (PREPARING) is resumed
                # or failed by the readopt loop; it must not hang forever.
                for pid, p in data.get("pgs", {}).items():
                    self._pgs[pid] = dict(p)
                # hot runtime-env keys survive head changes (stored as
                # AGES — monotonic stamps don't cross processes): a node
                # joining right after a failover still gets its
                # warm-onboarding hints
                for key, rec in data.get("hot_envs", {}).items():
                    self._hot_envs[key] = {
                        "last_seen": now - float(rec.get("age_s", 0.0)),
                        "runtime_env": rec.get("runtime_env")}
            logger.info("GCS restored %d KV namespaces, %d jobs, %d actor "
                        "records, %d nodes, %d placement groups from %s",
                        len(self._kv), len(data.get("jobs", {})),
                        len(data.get("actor_meta", {})),
                        len(data.get("nodes", {})), len(data.get("pgs", {})),
                        self._snapshot_uri)
        except Exception:
            logger.exception("snapshot restore failed; starting fresh")

    def _write_snapshot(self) -> None:
        import pickle

        with self._snapshot_write_lock:  # stop() vs loop: one writer at a time
            if self._lease is not None:
                # fencing gate: a stale head's snapshot write is REJECTED,
                # not raced — the standby that bumped the epoch owns the
                # store now (split-brain prevention, proven by
                # test_head_failover.py's revived-head test)
                from ray_tpu.core.head_lease import LeaseLostError

                try:
                    self._lease.check(self.fence_epoch)
                except LeaseLostError:
                    self._reject_fenced_write("snapshot_save")
                    raise
            with self._lock:
                data = {"session_id": self.session_id,
                        "fence_epoch": self.fence_epoch,
                        "kv": {ns: dict(t) for ns, t in self._kv.items()},
                        # function table: actor restart after a GCS restart
                        # resolves class blobs from here
                        "functions": dict(self._functions),
                        "jobs": dict(self._jobs),
                        # durable actor metadata: restart budgets, names and
                        # owners survive a GCS restart (reference persists the
                        # actor table in Redis, gcs_table_storage.h:50)
                        "actor_meta": {
                            aid: {"name": i.name, "namespace": i.namespace,
                                  "max_restarts": i.max_restarts,
                                  "num_restarts": i.num_restarts,
                                  "class_name": i.class_name,
                                  "owner": self._actor_owners.get(aid, ""),
                                  # full creation spec: restart-on-failure of
                                  # a restored actor needs the class blob
                                  "spec": self._actor_specs.get(aid)}
                            for aid, i in self._actors.items()
                            if i.state != ActorState.DEAD},
                        # node table: a replacement head must know which
                        # raylets to dial (per-node live stats stay out —
                        # they are rebuilt from heartbeats)
                        "nodes": {
                            nid: {k: n.get(k) for k in (
                                "node_id", "address", "object_store_address",
                                "resources_total", "resources_available",
                                "labels", "start_time", "incarnation")}
                            for nid, n in self._nodes.items() if n["alive"]},
                        # incarnation fencing survives head failover: the
                        # per-identity counters (for live nodes) and the
                        # invalidated identities — a zombie that heartbeats
                        # the REPLACEMENT head still gets fenced
                        "node_incarnations": {
                            nid: inc for nid, inc
                            in self._node_incarnations.items()
                            if nid in self._nodes},
                        "dead_nodes": list(self._dead_node_ids)[-4096:],
                        # failure-domain counters: a promoted head keeps
                        # reporting cumulative cluster history, not a
                        # counter reset (gcs_stats consistency across
                        # failover)
                        "node_failure_counters": {
                            "deaths": dict(self._node_deaths),
                            "drains": self._node_drains,
                            "fences": self._node_fences,
                            "quarantines": self._node_quarantines,
                            "quarantine_recoveries":
                                self._quarantine_recoveries,
                            "stale_rejections":
                                dict(self._stale_rejections)},
                        # placement groups with their bundle->node
                        # assignments: raylets keep the reservations, the
                        # head keeps the map (satellite: a restored head
                        # must not forget PGs whose bundles still run)
                        "pgs": {pid: dict(p)
                                for pid, p in self._pgs.items()},
                        # hot env keys as AGES (monotonic stamps don't
                        # cross processes): warm onboarding survives a
                        # head replacement
                        "hot_envs": {
                            k: {"age_s": max(0.0, time.monotonic()
                                             - rec.get("last_seen", 0.0)),
                                "runtime_env": rec.get("runtime_env")}
                            for k, rec in self._hot_envs.items()
                            if time.monotonic() - rec.get("last_seen", 0.0)
                            <= self._HOT_ENV_TTL_S}}
                self._dirty = False
            try:
                self._snapshot_last_version = self._snapshots.save(
                    pickle.dumps(data, protocol=5))
                self._snapshots_written += 1
            except Exception:
                self._dirty = True  # failed write must be retried
                raise

    def _snapshot_loop(self) -> None:
        while not self._shutdown.wait(self._snapshot_interval_s):
            if self._fenced.is_set():
                return  # a newer head owns the store; stop retrying writes
            if self._dirty:
                try:
                    self._write_snapshot()
                except Exception:
                    logger.exception("snapshot write failed")
        # stop() performs the final flush (single writer, serialized above)

    def _readopt_loop(self) -> None:
        """Replacement/promoted-head re-adoption: dial every snapshot-known
        raylet with a fencing-epoch'd `promote_announce` (the in-band
        'callback' flavor of re-resolution — works with no address file). A
        raylet of the SAME cluster session replies with its full
        registration payload in that ONE round trip, so it is adopted as a
        live node immediately — no full re-registration on the failover
        critical path (its reconnect loop still re-subscribes in the
        background, idempotently). Then resume any placement-group creation
        the old head died inside: with idempotent prepare_bundle on the
        raylets, re-running the 2-phase protocol either completes the PG or
        marks it INFEASIBLE — clients polling it never hang."""
        with self._lock:
            targets = dict(self._restored_nodes)
        for address, node_id in targets.items():
            if self._shutdown.is_set():
                return
            self._announce_to(address, node_id)
        # interrupted 2-phase creations: finish or fail them
        with self._lock:
            preparing = [pid for pid, p in self._pgs.items()
                         if p.get("state") == "PREPARING"]
        for pid in preparing:
            if self._shutdown.is_set():
                return
            with self._lock:
                p = self._pgs.get(pid)
                if p is None or p.get("state") != "PREPARING":
                    continue
                bundles, strategy, name = p["bundles"], p["strategy"], p.get("name")
            try:
                result = self._create_placement_group(pid, bundles, strategy,
                                                      name)
            except Exception as e:
                # one bad resume must not kill the thread and strand every
                # LATER interrupted group in PREPARING forever
                logger.exception("resume of placement group %s failed", pid)
                result = {"ok": False, "error": f"resume failed: {e}"}
            if not result.get("ok"):
                with self._lock:
                    p = self._pgs.get(pid)
                    if p is not None and p.get("state") != "CREATED":
                        p["state"] = "INFEASIBLE"
                        p["error"] = result.get("error", "resume failed")
                        self._dirty = True
                logger.warning("placement group %s interrupted by head "
                               "replacement could not be completed: %s",
                               pid, result.get("error"))

    def _announce_to(self, address: str, node_id: bytes) -> bool:
        """Dial one snapshot-known raylet and announce this head, carrying
        the fencing epoch + session id. Same-session raylets reply with
        their registration payload (one-RPC re-adoption); a raylet that
        already adopted a NEWER head rejects us — we are stale, fence.
        Returns True when the node left the provisional set."""
        try:
            client = rpc.connect_with_retry(address, timeout=5,
                                            origin=self._server.address)
        except Exception:
            # raylet gone with the old head; the heartbeat timeout will
            # reap its restored entry
            logger.info("restored node %s at %s unreachable",
                        node_id.hex()[:8], address)
            return False
        reply = None
        try:
            reply = client.call("promote_announce", {
                "address": self._server.address,
                "epoch": self.fence_epoch,
                "session_id": self.session_id,
            }, timeout=5)
        except rpc.RpcCallError:
            # raylet predates promote_announce: legacy one-way announce
            # (now also epoch-stamped so a stale head still gets dropped)
            try:
                client.notify("new_gcs_address",
                              {"address": self._server.address,
                               "epoch": self.fence_epoch})
            except OSError:
                client.close()
                return False
        except (OSError, TimeoutError, rpc.RpcDisconnected):
            client.close()
            return False
        if isinstance(reply, dict) and reply.get("adopted"):
            # one-RPC re-adoption: the reply IS the registration payload
            self._adopt_node(reply, client)
            return True
        if isinstance(reply, dict) and reply.get("reason") == "stale_epoch":
            client.close()
            self._reject_fenced_write("announce")
            return False
        # announced (legacy or session mismatch): the raylet's kicked
        # reconnect loop re-registers the normal way
        with self._lock:
            n = self._nodes.get(node_id)
            if n is not None and n.get("restored"):
                old = self._raylet_clients.get(node_id)
                self._raylet_clients[node_id] = client
                self._last_heartbeat[node_id] = time.monotonic()
            else:
                # re-registration beat us: keep its client, drop ours
                old = client
        if old is not None:
            old.close()
        return False

    def _adopt_node(self, payload: dict, client: rpc.RpcClient) -> None:
        """Install a node from a promote_announce reply exactly as
        register_node would, reusing the announce connection as the
        dispatch client — the raylet is live without a second RPC."""
        node_id = payload["node_id"]
        self._install_node(payload, client)
        logger.info("re-adopted raylet %s in one RPC (session match)",
                    node_id.hex()[:8])

    _REANNOUNCE_PERIOD_S = 2.0

    def _maybe_reannounce_restored(self) -> None:
        """Health-loop backstop for the one-shot readopt pass: keep dialing
        nodes still provisional ('restored') — a raylet unreachable during
        promotion deserves more than one chance before the heartbeat reaper
        takes it. Paced, off-thread, one pass at a time; every dial carries
        the fencing epoch (satellite: no epoch-less announces anywhere)."""
        now = time.monotonic()
        with self._lock:
            if getattr(self, "_reannounce_active", False):
                return
            last = getattr(self, "_last_reannounce", 0.0)
            if not self._restored_nodes \
                    or now - last < self._REANNOUNCE_PERIOD_S:
                return
            self._reannounce_active = True
            self._last_reannounce = now
            targets = dict(self._restored_nodes)

        def run():
            try:
                for address, node_id in targets.items():
                    if self._shutdown.is_set():
                        return
                    self._announce_to(address, node_id)
            finally:
                with self._lock:
                    self._reannounce_active = False

        threading.Thread(target=run, name="gcs-reannounce",
                         daemon=True).start()

    @property
    def address(self) -> str:
        return self._server.address

    def stop(self) -> None:
        self._shutdown.set()
        if self._snapshots is not None and self._dirty \
                and not self._fenced.is_set():
            from ray_tpu.core.head_lease import LeaseLostError

            try:
                self._write_snapshot()
            except LeaseLostError:
                logger.warning("final snapshot flush fenced: a newer head "
                               "owns the store")
            except OSError:
                logger.exception("final snapshot flush failed")
        for c in self._raylet_clients.values():
            c.close()
        self._server.stop()

    def kill(self) -> None:
        """Crash-stop for HA tests: tear the process-level state down the
        way a SIGKILLed head would leave it — NO final snapshot flush (a
        replacement restores from whatever the periodic loop last wrote),
        connections just dropped."""
        self._shutdown.set()
        for c in self._raylet_clients.values():
            c.close()
        self._server.stop()

    # ---------------------------------------------------------------- pubsub
    def _publish(self, channel: str, message: Any) -> None:
        # Partition-aware fan-out: pushes ride server->client connections,
        # which the client-send FaultInjector never sees — consult the
        # partition rules directly so a blackholed side receives no pubsub
        # either (a partitioned raylet must not learn cluster events).
        inj = rpc.get_fault_injector()
        me = self._server.address if inj is not None else None
        for conn in list(self._subs.get(channel, [])):
            if not conn.alive:
                continue
            if inj is not None and conn.origin is not None \
                    and inj.partition_drop(me, conn.origin):
                continue
            conn.push("pubsub", {"channel": channel, "message": message})

    def rpc_subscribe(self, conn, req_id, payload):
        channels = payload["channels"]
        origin = payload.get("origin")
        if origin:
            # the subscriber's NODE identity: lets the partition injector
            # judge pushes on this connection (see _publish)
            conn.origin = origin
        for ch in channels:
            subs = self._subs.setdefault(ch, [])
            if conn not in subs:
                subs.append(conn)
                conn.on_close.append(lambda c, ch=ch: self._unsub(ch, c))
        if CH_RESOURCES in channels:
            # a fresh subscriber has no base view to apply deltas onto
            with self._lock:
                self._bcast_full_needed = True
        return True

    def rpc_publish(self, conn, req_id, payload):
        """Generic application-level publish: fan a message out to every
        subscriber of an arbitrary channel (reference GcsPublisher allows
        app channels the same way, pubsub.proto:28-46). Serve's controller
        uses this to PUSH replica-set version bumps to handles instead of
        parking their long-polls on its exec threads."""
        self._publish(payload["channel"], payload["message"])
        return True

    def rpc_unsubscribe(self, conn, req_id, payload):
        for ch in payload["channels"]:
            self._unsub(ch, conn)
        return True

    def _unsub(self, channel: str, conn) -> None:
        try:
            self._subs.get(channel, []).remove(conn)
        except ValueError:
            pass

    def rpc_publish_logs(self, conn, req_id, payload):
        """Raylet-forwarded worker stdout/stderr -> CH_LOGS subscribers
        (the reference's log_monitor tail-to-driver, log_monitor.py)."""
        self._recent_logs.append(payload)
        self._publish(CH_LOGS, payload)
        return True

    def rpc_get_recent_logs(self, conn, req_id, payload):
        """Last `lines` individual log lines, flattened across publish
        batches (one entry per line, newest last)."""
        n = payload.get("lines", 200) if payload else 200
        if n <= 0:
            return []
        flat = []
        for entry in self._recent_logs:
            for line in entry.get("lines", []):
                flat.append({"pid": entry.get("pid"),
                             "stream": entry.get("stream"),
                             "node_id": entry.get("node_id"),
                             "lines": [line]})
        return flat[-n:]

    def rpc_global_gc(self, conn, req_id, payload):
        """Broadcast a gc request to every raylet -> every worker
        (reference `ray global_gc`, scripts.py:2161)."""
        self._publish(CH_CONTROL, {"cmd": "gc"})
        return True

    # ----------------------------------------------------------------- nodes
    def _count_stale(self, site: str) -> None:
        with self._lock:
            self._stale_rejections[site] = \
                self._stale_rejections.get(site, 0) + 1
        try:
            _node_metrics()["stale_rejections"].inc(tags={"site": site})
        except Exception:
            pass

    def _fence_node_reply(self, node_id: bytes, site: str,
                          reason: str) -> dict:
        """Typed fence response for a node presenting an invalidated
        identity: the raylet that receives it kills its workers (their
        actor incarnations were restarted elsewhere while it was declared
        dead) and rejoins as a FRESH node."""
        with self._lock:
            self._node_fences += 1
            self._dirty = True  # counters are snapshot state
        self._count_stale(site)
        try:
            _node_metrics()["fenced"].inc()
        except Exception:
            pass
        logger.warning("fencing node %s at %s: %s", node_id.hex()[:8],
                       site, reason)
        return {"fenced": True, "reason": reason, "site": site,
                "epoch": self.fence_epoch}

    def rpc_register_node(self, conn, req_id, payload):
        node_id: bytes = payload["node_id"]
        with self._lock:
            n = self._nodes.get(node_id)
            dead = (node_id in self._dead_node_ids
                    or (n is not None and not n.get("alive", True)))
        if dead:
            # a node identity declared dead can never re-register: the
            # cluster already acted on its death (actors restarted,
            # autoscaler replaced it) — the zombie must rejoin fresh
            return self._fence_node_reply(
                node_id, "register",
                "node identity was declared dead; rejoin with a fresh id")
        self._install_node(payload)
        with self._lock:
            nodes = [self._public_node(n) for n in self._nodes]
            hot = self._hot_envs_payload_locked()
            incarnation = self._node_incarnations.get(node_id, 0)
        # epoch + session ride the reply: the raylet uses the epoch to fence
        # stale-head announces and the session id as its re-adoption
        # fingerprint across head promotions; hot_envs is the warm-onboarding
        # hint — the joiner pre-spawns fork templates for these keys so a
        # replacement node serves warm leases immediately. The incarnation
        # is the node's fencing token: heartbeats echo it back.
        return {"nodes": nodes, "epoch": self.fence_epoch,
                "session_id": self.session_id, "hot_envs": hot,
                "incarnation": incarnation}

    def _install_node(self, payload: dict,
                      client: Optional[rpc.RpcClient] = None) -> None:
        """Shared node-installation path for register_node and the
        promote_announce one-RPC re-adoption (which passes the announce
        connection as the dispatch `client`)."""
        node_id: bytes = payload["node_id"]
        with self._lock:
            stale = self._raylet_clients.pop(node_id, None)
            # Incarnation stamping: a raylet re-registering with the
            # incarnation it already holds (link blip, head re-adoption)
            # KEEPS it — no bump, so an in-flight heartbeat can't race a
            # re-register into a spurious mismatch. A fresh join (no or
            # older incarnation) gets the identity's next monotonic value.
            known = self._node_incarnations.get(node_id, 0)
            offered = int(payload.get("incarnation") or 0)
            incarnation = offered if offered >= known and offered > 0 \
                else known + 1
            self._node_incarnations[node_id] = incarnation
            self._nodes[node_id] = {
                "node_id": node_id,
                "address": payload["address"],
                "object_store_address": payload.get("object_store_address", payload["address"]),
                "resources_total": dict(payload["resources"]),
                # re-registration after a GCS restart reports true availability
                "resources_available": dict(
                    payload.get("resources_available", payload["resources"])),
                "labels": payload.get("labels", {}),
                "alive": True,
                "incarnation": incarnation,
                "start_time": payload.get("start_time") or time.time(),
            }
            self._restored_nodes.pop(payload["address"], None)
            self._last_heartbeat[node_id] = time.monotonic()
            self._dirty = True  # membership is snapshot state
            self._bcast_dirty.add(node_id.hex())
            self._bcast_removed.discard(node_id.hex())
            self._bcast_full_needed = True  # topology: next publish is full
            if client is not None:
                self._raylet_clients[node_id] = client
            else:
                try:
                    self._raylet_clients[node_id] = rpc.connect_with_retry(
                        payload["address"], timeout=10,
                        origin=self._server.address)
                except Exception:
                    logger.exception("GCS could not connect back to raylet %s", payload["address"])
            # fresh capacity: every capacity-starved restart is due NOW
            for aid in self._pending_restarts:
                self._pending_restarts[aid] = 0.0
        if stale is not None and stale is not client:
            stale.close()
        # Bundle re-pinning: the raylet reports the PG bundle reservations
        # it still holds. A head replacement may have restored a snapshot
        # older than a commit — adopt the raylet's committed bundles into
        # the known PG table so placement reflects what the fleet actually
        # holds (the raylet, not the snapshot, is the source of truth for
        # reservations it charged).
        stale_bundles = []
        with self._lock:
            for b in payload.get("bundles", ()):
                pg = self._pgs.get(b["pg_id"])
                if pg is None or not b.get("committed"):
                    continue
                placement = pg.get("placement")
                idx = b["bundle_index"]
                if placement is None or idx >= len(placement) \
                        or placement[idx] == node_id:
                    continue
                holder = self._nodes.get(placement[idx])
                if holder is not None and holder.get("alive"):
                    # the bundle was rescheduled onto a LIVE node while
                    # this raylet was away (falsely-dead node, heartbeat
                    # starvation, re-registering after the bundle resched
                    # moved its bundles): this raylet's reservation is the
                    # stale one — return it instead of stealing the
                    # placement back and leaking the live holder's charge
                    stale_bundles.append((b["pg_id"], idx))
                else:
                    placement[idx] = node_id
                    self._dirty = True
        for pg_id, idx in stale_bundles:
            c = self._raylet_client(node_id)
            if c is None:
                break
            try:
                c.notify("return_bundle",
                         {"pg_id": pg_id, "bundle_index": idx})
                logger.warning("raylet %s re-registered holding bundle "
                               "(%s, %d) that was rescheduled; returning "
                               "its stale reservation",
                               node_id.hex()[:8], pg_id, idx)
            except OSError:
                pass
        self._publish(CH_NODES, {"event": "added", "node": self._public_node(node_id)})
        self._broadcast_resources(force=True)

    def _public_node(self, node_id: bytes) -> dict:
        n = self._nodes[node_id]
        out = {k: n[k] for k in (
            "node_id", "address", "object_store_address", "resources_total",
            "resources_available", "labels", "alive")}
        if n.get("stats"):
            out["stats"] = n["stats"]
        if n.get("join_to_first_warm_lease_s") is not None:
            # warm-onboarding observability: how long this node took from
            # join to its first forked lease (set once, by report_warm_lease)
            out["join_to_first_warm_lease_s"] = n["join_to_first_warm_lease_s"]
        return out

    def rpc_heartbeat(self, conn, req_id, payload):
        node_id = payload["node_id"]
        with self._lock:
            n = self._nodes.get(node_id)
            dead = (node_id in self._dead_node_ids
                    or (n is not None and not n.get("alive", True)))
        if dead:
            # zombie raylet (declared dead during a partition, network
            # healed): its identity is invalidated — typed fence reply
            # makes it kill its workers and rejoin as a fresh node
            return self._fence_node_reply(
                node_id, "heartbeat",
                "heartbeat from a node identity declared dead")
        if n is None:
            # unknown (not invalidated) identity: a registration this head
            # never saw (e.g. landed after the snapshot a replacement head
            # restored). Not a fence — the raylet just re-registers.
            return {"unknown": True}
        recovered = False
        with self._lock:
            self._last_heartbeat[node_id] = time.monotonic()
            n = self._nodes.get(node_id)
            if n is not None and n.pop("quarantined", None):
                # gray-failure recovery: heartbeats resumed before the
                # death bound — the node rejoins scheduling with its
                # actors/leases intact, no replacement launched
                self._quarantine_recoveries += 1
                self._dirty = True  # counters are snapshot state
                self._bcast_dirty.add(node_id.hex())
                self._bcast_full_needed = True
                recovered = True
        if recovered:
            logger.warning("node %s recovered from quarantine (heartbeats "
                           "resumed)", node_id.hex()[:8])
            self._publish(CH_NODES, {"event": "recovered",
                                     "node_id": node_id})
            self._broadcast_resources(force=True)
        with self._lock:
            n = self._nodes.get(node_id)
            if n is not None and "resources_available" in payload:
                if n["resources_available"] != payload["resources_available"]:
                    self._bcast_dirty.add(node_id.hex())
                n["resources_available"] = payload["resources_available"]
            if n is not None:
                n["pending_demands"] = payload.get("pending_demands", [])
                # per-node physical utilization (reference reporter agent):
                # ALWAYS overwritten (an empty report clears the entry —
                # stale samples must not masquerade as live data) and
                # timestamped so readers can judge freshness
                stats = payload.get("node_stats") or {}
                if stats:
                    stats["sampled_at"] = time.time()
                    n["stats"] = stats
                else:
                    n.pop("stats", None)
            # hot runtime-env tracking (warm node onboarding): raylets
            # report env keys with recent lease traffic; joiners get the
            # fleet-wide view in their register_node reply
            now_mono = time.monotonic()
            for ent in payload.get("hot_envs", ()):
                key = ent.get("env_key")
                rec = self._hot_envs.setdefault(key, {})
                rec["last_seen"] = now_mono
                if ent.get("runtime_env") is not None:
                    rec["runtime_env"] = ent["runtime_env"]
            # opportunistic prune: keys cold past the TTL leave the table
            # (and the snapshot) instead of accumulating across env churn
            for key in [k for k, rec in self._hot_envs.items()
                        if now_mono - rec.get("last_seen", 0.0)
                        > self._HOT_ENV_TTL_S]:
                del self._hot_envs[key]
        return True

    _HOT_ENV_TTL_S = 600.0

    def _hot_envs_payload_locked(self) -> list:
        """Caller holds self._lock. Recently-hot env keys (most recent
        first, capped) for a joining raylet's template prewarm."""
        now = time.monotonic()
        out = []
        for key, rec in sorted(self._hot_envs.items(),
                               key=lambda kv: -kv[1].get("last_seen", 0.0)):
            if now - rec.get("last_seen", 0.0) > self._HOT_ENV_TTL_S:
                continue
            out.append({"env_key": key,
                        "runtime_env": rec.get("runtime_env")})
            if len(out) >= 8:
                break
        return out

    def rpc_autoscaler_report(self, conn, req_id, payload):
        """The autoscaler's reconcile counters (launches, relaunches,
        deaths seen, breaker state), refreshed every tick; surfaced via
        gcs_stats so node-level recovery is observable in one place."""
        with self._lock:
            self._autoscaler_stats = dict(payload or {})
        return True

    def rpc_report_warm_lease(self, conn, req_id, payload):
        """A joined raylet served its first WARM (forked) lease: the far
        edge of node-join-to-first-warm-lease — the number warm onboarding
        exists to shrink."""
        sample = {"node_id": payload["node_id"].hex(),
                  "join_to_first_warm_lease_s":
                      float(payload["join_to_first_warm_lease_s"]),
                  "at": time.time()}
        with self._lock:
            self._warm_lease_joins.append(sample)
            n = self._nodes.get(payload["node_id"])
            if n is not None:
                n["join_to_first_warm_lease_s"] = \
                    sample["join_to_first_warm_lease_s"]
        try:
            _node_metrics()["join_warm"].set(
                sample["join_to_first_warm_lease_s"])
        except Exception:
            pass
        return True

    def rpc_get_pending_demands(self, conn, req_id, payload):
        """Aggregate unscheduled resource demand (autoscaler input; reference
        load_metrics.py)."""
        with self._lock:
            out = []
            for n in self._nodes.values():
                if n["alive"]:
                    out.extend(n.get("pending_demands", []))
            return out

    def rpc_report_resources(self, conn, req_id, payload):
        """Raylet resource view update (reference RaySyncer role)."""
        node_id = payload["node_id"]
        with self._lock:
            n = self._nodes.get(node_id)
            if n is not None:
                n["resources_available"] = payload["available"]
                self._bcast_dirty.add(node_id.hex())
        self._broadcast_resources()
        return True

    def _broadcast_resources(self, force: bool = False) -> None:
        """Debounced CH_RESOURCES fan-out: every subscribed raylet runs a
        scheduling pass on each broadcast, so per-completion rebroadcasts
        multiplied control-plane work by the node count. At most one publish
        per resource_broadcast_period_ms; a burst arms one trailing timer so
        the final view always lands. Topology changes (node added/removed)
        pass force=True — membership must never wait out a debounce."""
        self._bcast_debounce(force=force)

    def _publish_resources(self) -> None:
        """One CH_RESOURCES publish: a per-node DELTA of the views that
        changed since the last publish (so steady-state gossip is O(changed
        nodes), not O(nodes) payload x O(nodes) subscribers — the former
        full-snapshot fan-out was O(nodes²) bytes at fleet scale), or a
        FULL snapshot on topology change / new subscriber / first publish.
        Every message carries a sequence number (raylets detect gaps and
        catch up via get_resources_full) and the fencing epoch (a stale
        head's publishes are ignored)."""
        import pickle as _pickle

        with self._lock:
            subs = len(self._subs.get(CH_RESOURCES, ()))
            self._bcast_seq += 1
            seq = self._bcast_seq
            full = (self._bcast_full_needed
                    or not get_config().resource_broadcast_delta_enabled)
            if full:
                msg = {"kind": "full", "seq": seq, "epoch": self.fence_epoch,
                       "nodes": self._cluster_view_locked()}
                self._bcast_fulls += 1
                self._bcast_full_needed = False
            else:
                changed = {}
                for hexid in self._bcast_dirty:
                    try:
                        n = self._nodes.get(bytes.fromhex(hexid))
                    except ValueError:
                        continue
                    if n is not None and n["alive"]:
                        changed[hexid] = self._node_view(n)
                msg = {"kind": "delta", "seq": seq, "prev": seq - 1,
                       "epoch": self.fence_epoch, "changed": changed,
                       "removed": sorted(self._bcast_removed)}
                self._bcast_deltas += 1
            self._bcast_dirty.clear()
            self._bcast_removed.clear()
        # accounting (bytes that hit subscriber sockets) rides the same
        # pickle the rpc layer would produce; one dumps per debounce period
        try:
            self._bcast_bytes += len(_pickle.dumps(msg, protocol=5)) \
                * max(1, subs)
        except Exception:
            pass
        self._publish(CH_RESOURCES, msg)

    def rpc_get_resources_full(self, conn, req_id, payload):
        """Subscriber catch-up: a raylet that missed a delta (gap in the
        sequence) pulls one consistent full view + the seq it is current
        as of, then resumes applying deltas from there."""
        with self._lock:
            return {"kind": "full", "seq": self._bcast_seq,
                    "epoch": self.fence_epoch,
                    "nodes": self._cluster_view_locked()}

    @staticmethod
    def _node_view(n: dict) -> dict:
        return {
            "address": n["address"],
            "object_store_address": n["object_store_address"],
            "total": dict(n["resources_total"]),
            "available": dict(n["resources_available"]),
            "labels": dict(n["labels"]),
            "alive": n["alive"],
            # quarantined nodes stay ALIVE (no replacement, actors kept)
            # but take no NEW dispatch anywhere in the fleet
            "quarantined": bool(n.get("quarantined")),
        }

    def _cluster_view_locked(self) -> dict:
        return {nid.hex(): self._node_view(n)
                for nid, n in self._nodes.items()}

    def cluster_view(self) -> dict:
        with self._lock:
            return self._cluster_view_locked()

    def rpc_get_cluster_view(self, conn, req_id, payload):
        return self.cluster_view()

    def rpc_get_all_nodes(self, conn, req_id, payload):
        with self._lock:
            return [self._public_node(n) for n in self._nodes]

    def rpc_drain_node(self, conn, req_id, payload):
        """Graceful removal (autoscaler downscale)."""
        self._mark_node_dead(payload["node_id"], "drained")
        return True

    def _health_loop(self) -> None:
        cfg = get_config()
        period = cfg.health_check_period_ms / 1000.0
        timeout = cfg.health_check_timeout_ms / 1000.0
        # gray-failure quarantine bound: strictly INSIDE the death bound
        # (0 = half of it), so a degraded node stops receiving new
        # dispatch before it is declared dead — and crash-stop detection
        # latency is untouched (the death check below is independent)
        q_ms = cfg.node_quarantine_timeout_ms
        quarantine_s = (q_ms / 1000.0) if q_ms > 0 else timeout / 2.0
        quarantine_s = min(quarantine_s, timeout * 0.9)
        while not self._shutdown.wait(period):
            now = time.monotonic()
            dead = []
            suspects = []
            with self._lock:
                for nid, last in self._last_heartbeat.items():
                    n = self._nodes.get(nid, {})
                    if not n.get("alive"):
                        continue
                    if now - last > timeout:
                        dead.append(nid)
                    elif now - last > quarantine_s \
                            and not n.get("quarantined"):
                        n["quarantined"] = True
                        self._node_quarantines += 1
                        self._dirty = True  # counters are snapshot state
                        self._bcast_dirty.add(nid.hex())
                        self._bcast_full_needed = True
                        suspects.append(nid)
            for nid in suspects:
                logger.warning(
                    "node %s heartbeat delivery degraded (> %.1fs silent); "
                    "QUARANTINED — no new dispatch, replacement held until "
                    "the %.1fs death bound", nid.hex()[:8], quarantine_s,
                    timeout)
                try:
                    _node_metrics()["quarantines"].inc()
                except Exception:
                    pass
                self._publish(CH_NODES, {"event": "quarantined",
                                         "node_id": nid})
            if suspects:
                self._broadcast_resources(force=True)
            for nid in dead:
                logger.warning("node %s missed heartbeats; marking dead", nid.hex()[:8])
                self._mark_node_dead(nid, "health check failed")
            # Reap snapshot-restored actors whose worker never re-announced
            # (the process died together with the old GCS's view of it).
            reap = []
            with self._lock:
                for aid, since in list(self._awaiting_rereg.items()):
                    if now - since > 60.0:
                        self._awaiting_rereg.pop(aid, None)
                        info = self._actors.get(aid)
                        if info is not None and info.state == ActorState.RESTARTING:
                            reap.append(aid)
            for aid in reap:
                with self._lock:
                    info = self._actors[aid]
                    info.state = ActorState.DEAD
                    info.death_cause = "did not re-register after GCS restart"
                    self._dirty = True
                self._publish(CH_ACTORS, {
                    "actor_id": aid, "state": "DEAD", "address": "",
                    "death_cause": info.death_cause})
            # PENDING placement groups are retryable (transient prepare
            # failure, capacity that has since arrived): re-run their 2PC
            # off-thread, paced, so a blip never strands a group forever.
            self._maybe_retry_pending_pgs()
            # actors whose restart found no capacity (node death ahead of
            # the replacement) retry here until a node can hold them
            self._maybe_retry_actor_restarts()
            # bundles stranded on dead nodes move to live capacity
            self._maybe_reschedule_lost_bundles()
            # still-provisional snapshot-restored nodes get re-dialed (with
            # the fencing epoch) until they adopt us or the reaper wins
            self._maybe_reannounce_restored()
            # driver-death backstop: RUNNING jobs with no live conn and
            # snapshot-restored unreaped jobs get probed within
            # job_reap_detection_bound_s
            self._maybe_probe_dead_drivers(time.monotonic())

    _RESTART_RETRY_INTERVAL_S = 1.0

    def _maybe_retry_actor_restarts(self) -> None:
        """Paced, off-thread re-scheduling of RESTARTING actors that had no
        capacity at failure time (reference GcsActorManager keeps such
        actors PENDING until a node can hold them). A node registration
        makes every entry immediately due (_install_node)."""
        now = time.monotonic()
        with self._lock:
            if self._restart_retry_active or self._shutdown.is_set():
                return
            due = [aid for aid, t in self._pending_restarts.items()
                   if now >= t]
            if not due:
                return
            self._restart_retry_active = True

        def run():
            try:
                pending_timeout = get_config().actor_restart_pending_timeout_s
                for aid in due:
                    if self._shutdown.is_set():
                        return
                    expired = None
                    with self._lock:
                        info = self._actors.get(aid)
                        if info is None \
                                or info.state != ActorState.RESTARTING:
                            self._pending_restarts.pop(aid, None)
                            self._pending_restart_since.pop(aid, None)
                            continue
                        since = self._pending_restart_since.get(aid)
                        if since is not None and pending_timeout > 0 and \
                                time.monotonic() - since > pending_timeout:
                            # the wait is bounded: a restart nothing can
                            # ever place (node type unlaunchable, breaker
                            # stuck open) must fail typed, not hang refs
                            info.state = ActorState.DEAD
                            info.death_cause = (
                                "restart failed: no feasible capacity "
                                f"within {pending_timeout:.0f}s")
                            self._pending_restarts.pop(aid, None)
                            self._pending_restart_since.pop(aid, None)
                            self._dirty = True
                            expired = info
                    if expired is not None:
                        logger.warning("actor %s restart expired after "
                                       "%.0fs with no capacity; marking "
                                       "DEAD", aid, pending_timeout)
                        self._publish(CH_ACTORS, {
                            "actor_id": aid, "state": expired.state.value,
                            "address": "",
                            "death_cause": expired.death_cause})
                        continue
                    if self._schedule_actor(aid, require_available=True):
                        with self._lock:
                            self._pending_restarts.pop(aid, None)
                            self._pending_restart_since.pop(aid, None)
                    else:
                        with self._lock:
                            self._pending_restarts[aid] = time.monotonic() \
                                + self._RESTART_RETRY_INTERVAL_S
            finally:
                with self._lock:
                    self._restart_retry_active = False

        threading.Thread(target=run, name="gcs-actor-restart-retry",
                         daemon=True).start()

    _BUNDLE_RESCHED_INTERVAL_S = 2.0

    def _maybe_reschedule_lost_bundles(self) -> None:
        """CREATED placement groups with bundles on dead nodes get those
        bundles re-placed on surviving/replacement capacity (reference
        GcsPlacementGroupManager bundle rescheduling on node death). Only
        the LOST bundles move — surviving reservations are never touched,
        so no double-charge and no full re-placement churn."""
        now = time.monotonic()
        with self._lock:
            if self._bundle_resched_active or self._shutdown.is_set():
                return
            alive = {nid for nid, n in self._nodes.items() if n["alive"]}
            work = []
            for pid, p in self._pgs.items():
                if p.get("state") != "CREATED" or not p.get("placement"):
                    continue
                lost = [i for i, nid in enumerate(p["placement"])
                        if nid not in alive]
                if lost and now - p.get("_last_resched", 0.0) \
                        > self._BUNDLE_RESCHED_INTERVAL_S:
                    work.append((pid, lost))
            if not work:
                return
            self._bundle_resched_active = True

        def run():
            try:
                for pid, lost in work:
                    if self._shutdown.is_set():
                        return
                    try:
                        self._reschedule_bundles(pid, lost)
                    except Exception:
                        logger.exception("bundle reschedule of %s failed",
                                         pid)
            finally:
                with self._lock:
                    self._bundle_resched_active = False

        threading.Thread(target=run, name="gcs-bundle-resched",
                         daemon=True).start()

    def _reschedule_bundles(self, pg_id: PlacementGroupID,
                            lost_indices: List[int]) -> None:
        with self._lock:
            p = self._pgs.get(pg_id)
            if p is None or p.get("state") != "CREATED":
                return
            p["_last_resched"] = time.monotonic()
            bundles = p["bundles"]
            placement = list(p["placement"])
            strategy = p["strategy"]
            views = [
                NodeView(nid, n["resources_total"],
                         n["resources_available"], n["labels"])
                for nid, n in self._nodes.items()
                if n["alive"] and not n.get("quarantined")]
        held = {placement[i] for i in range(len(placement))
                if i not in lost_indices}
        for idx in lost_indices:
            bundle = bundles[idx]
            candidates = views
            if strategy == "STRICT_SPREAD":
                candidates = [v for v in views if v.node_id not in held]
            elif strategy == "STRICT_PACK":
                # co-locate with surviving bundles when possible; a strict
                # pack broken by node death prefers partial locality over
                # staying broken forever
                candidates = [v for v in views if v.node_id in held] or views
            avail = [v for v in candidates if v.is_available(bundle)]
            if not avail:
                continue  # paced retry finds replacement capacity later
            target = min(avail,
                         key=lambda v: (v.utilization(), v.node_id)).node_id
            client = self._raylet_client(target)
            if client is None:
                continue
            try:
                if not client.call("prepare_bundle", {
                        "pg_id": pg_id, "bundle_index": idx,
                        "resources": bundle}, timeout=10):
                    continue
                client.notify("commit_bundle",
                              {"pg_id": pg_id, "bundle_index": idx})
            except (OSError, TimeoutError, rpc.RpcCallError,
                    rpc.RpcDisconnected) as e:
                logger.info("bundle reschedule prepare on %s failed: %s",
                            target.hex()[:8], e)
                continue
            with self._lock:
                p = self._pgs.get(pg_id)
                if p is None or not p.get("placement") \
                        or idx >= len(p["placement"]):
                    # group removed while we re-placed: return the bundle
                    try:
                        client.notify("return_bundle", {
                            "pg_id": pg_id, "bundle_index": idx})
                    except OSError:
                        pass
                    continue
                p["placement"][idx] = target
                self._dirty = True
            held.add(target)
            logger.warning("rescheduled bundle (%s, %d) onto %s after node "
                           "death", pg_id, idx, target.hex()[:8])

    _PG_RETRY_INTERVAL_S = 5.0

    def _maybe_retry_pending_pgs(self) -> None:
        now = time.monotonic()
        with self._lock:
            if self._pg_retry_active or self._shutdown.is_set():
                return
            if not any(n["alive"] for n in self._nodes.values()):
                return
            due = [pid for pid, p in self._pgs.items()
                   if p.get("state") == "PENDING"
                   and now - p.get("_last_attempt", 0.0)
                   > self._PG_RETRY_INTERVAL_S]
            if not due:
                return
            self._pg_retry_active = True

        def run():
            try:
                for pid in due:
                    if self._shutdown.is_set():
                        return
                    with self._lock:
                        p = self._pgs.get(pid)
                        if p is None or p.get("state") != "PENDING":
                            continue
                        bundles, strategy = p["bundles"], p["strategy"]
                        name = p.get("name")
                    try:
                        self._create_placement_group(pid, bundles, strategy,
                                                     name)
                    except Exception:
                        logger.exception("retry of pending placement group "
                                         "failed")
                    finally:
                        # stamped AFTER the attempt (creation overwrites the
                        # entry) so the pace holds even across failures
                        with self._lock:
                            p = self._pgs.get(pid)
                            if p is not None:
                                p["_last_attempt"] = time.monotonic()
            finally:
                with self._lock:
                    self._pg_retry_active = False

        threading.Thread(target=run, name="gcs-pg-retry", daemon=True).start()

    def _raylet_client(self, node_id: bytes) -> Optional[rpc.RpcClient]:
        """Live dispatch client for a node, reconnecting a dead one (a
        severed link — injected fault, transient network blip — must not
        permanently cut the head off from an otherwise-alive raylet)."""
        with self._lock:
            c = self._raylet_clients.get(node_id)
            n = self._nodes.get(node_id)
        if c is not None and not c.closed:
            return c
        if n is None or not n.get("alive"):
            return None
        try:
            fresh = rpc.connect_with_retry(n["address"], timeout=3,
                                           origin=self._server.address)
        except Exception:
            logger.info("could not reconnect to raylet %s at %s",
                        node_id.hex()[:8], n["address"])
            return None
        with self._lock:
            cur = self._raylet_clients.get(node_id)
            if cur is not None and not cur.closed:
                keep = cur  # a re-registration raced us in; use its client
            else:
                self._raylet_clients[node_id] = fresh
                keep = fresh
        if keep is not fresh:
            fresh.close()
        return keep

    def _mark_node_dead(self, node_id: bytes, reason: str) -> None:
        with self._lock:
            n = self._nodes.get(node_id)
            if n is None or not n["alive"]:
                return
            n["alive"] = False
            n.pop("quarantined", None)
            # invalidate the identity: from here on, any heartbeat/register
            # presenting this node_id is a zombie and gets fenced. Bounded:
            # the OLDEST invalidations evict past the cap (zombies return
            # within heal timescales, not after 4096 later deaths).
            self._dead_node_ids[node_id] = None
            while len(self._dead_node_ids) > 4096:
                self._dead_node_ids.pop(next(iter(self._dead_node_ids)))
            self._restored_nodes.pop(n.get("address"), None)
            self._dirty = True  # membership is snapshot state
            self._bcast_removed.add(node_id.hex())
            self._bcast_dirty.discard(node_id.hex())
            self._bcast_full_needed = True  # topology: next publish is full
            client = self._raylet_clients.pop(node_id, None)
            tag = reason.replace(" ", "_")
            if tag == "drained":
                # graceful removal (autoscaler downscale, operator drain)
                # is not a DEATH: counting it would make the headline
                # failure metric fire on routine scale-down
                self._node_drains += 1
                tag = None
            else:
                self._node_deaths[tag] = self._node_deaths.get(tag, 0) + 1
        if tag is not None:
            try:
                _node_metrics()["deaths"].inc(tags={"reason": tag})
            except Exception:
                pass
        if client:
            client.close()
        self._publish(CH_NODES, {"event": "removed", "node_id": node_id, "reason": reason})
        self._broadcast_resources(force=True)
        # Fail over actors that lived on the dead node.
        with self._lock:
            affected = [a for a in self._actors.values() if a.node_id == node_id and a.state == ActorState.ALIVE]
        for info in affected:
            self._handle_actor_failure(info.actor_id, f"node {node_id.hex()[:8]} died: {reason}")
        # A creation/restart DISPATCHED to this node before it died will
        # never report actor_creation_done, and a successful dispatch left
        # _pending_restarts — nothing retries it. Re-park such actors
        # due-now for the paced retry (no budget charge: that incarnation
        # never ran). This is the kill-storm race — a second node kill
        # landing inside another restart's dispatch->done window.
        with self._lock:
            now = time.monotonic()
            stranded = []
            for a in self._actors.values():
                if a.node_id == node_id and a.state in (
                        ActorState.PENDING, ActorState.RESTARTING):
                    a.state = ActorState.RESTARTING
                    a.address = ""
                    self._pending_restarts[a.actor_id] = 0.0
                    self._pending_restart_since.setdefault(a.actor_id, now)
                    stranded.append(a.actor_id)
            if stranded:
                self._dirty = True
        for aid in stranded:
            logger.warning("actor %s creation was in flight on dead node "
                           "%s; re-parking for retry", aid,
                           node_id.hex()[:8])
            self._publish(CH_ACTORS, {"actor_id": aid, "state": "RESTARTING",
                                      "address": "", "death_cause": ""})
        # bundles the dead node held move to surviving/replacement nodes
        self._maybe_reschedule_lost_bundles()

    # ---------------------------------------------------------------- kv
    def rpc_kv_put(self, conn, req_id, payload):
        ns = payload.get("namespace", "")
        with self._lock:
            table = self._kv.setdefault(ns, {})
            exists = payload["key"] in table
            if payload.get("overwrite", True) or not exists:
                table[payload["key"]] = payload["value"]
                self._dirty = True
                return True
            return False

    def rpc_kv_get(self, conn, req_id, payload):
        ns = payload.get("namespace", "")
        with self._lock:
            return self._kv.get(ns, {}).get(payload["key"])

    def rpc_kv_del(self, conn, req_id, payload):
        ns = payload.get("namespace", "")
        with self._lock:
            removed = self._kv.get(ns, {}).pop(payload["key"], None) is not None
            self._dirty = self._dirty or removed
            return removed

    def rpc_kv_keys(self, conn, req_id, payload):
        ns = payload.get("namespace", "")
        prefix = payload.get("prefix", b"")
        with self._lock:
            return [k for k in self._kv.get(ns, {}) if k.startswith(prefix)]

    def rpc_kv_exists(self, conn, req_id, payload):
        ns = payload.get("namespace", "")
        with self._lock:
            return payload["key"] in self._kv.get(ns, {})

    # ------------------------------------------------------- function table
    def rpc_function_put(self, conn, req_id, payload):
        """Export-once function/class blob, keyed by content hash
        (reference function_manager.py export to GCS). Idempotent: the same
        id always maps to the same bytes, so a duplicate put (replay after
        a GCS restart, two submitters racing) is a no-op."""
        with self._lock:
            self._function_puts += 1
            jid = payload.get("job_id")
            if jid is not None:
                # job ownership index: the fate-sharing reap frees an
                # export only when the dead job was its LAST owner
                self._function_jobs.setdefault(
                    payload["function_id"], set()).add(jid)
            if payload["function_id"] not in self._functions:
                self._functions[payload["function_id"]] = payload["blob"]
                self._function_bytes += len(payload["blob"])
                self._dirty = True
                # Byte-budget FIFO eviction: a driver minting unbounded
                # DISTINCT closures (new lambda per batch) must not grow
                # the table and its snapshot forever. An evicted function
                # fails its executor fetch — loudly, and only in that
                # pathological pattern (steady workloads re-use ids).
                budget = get_config().function_table_max_bytes
                while self._function_bytes > budget and len(self._functions) > 1:
                    old_id = next(iter(self._functions))
                    self._function_bytes -= len(self._functions.pop(old_id))
                    self._function_jobs.pop(old_id, None)
                    self._function_evictions += 1
                    logger.warning(
                        "function table over %d bytes; evicted oldest "
                        "export %s (%d evictions total) — raise "
                        "RAY_TPU_FUNCTION_TABLE_MAX_BYTES or stop "
                        "creating distinct closures per submission",
                        budget, old_id.hex()[:12], self._function_evictions)
        return True

    def rpc_function_get(self, conn, req_id, payload):
        """Executor miss path: fetch a blob for local deserialization."""
        with self._lock:
            return self._functions.get(payload["function_id"])

    def rpc_function_table_stats(self, conn, req_id, payload):
        with self._lock:
            return {"entries": len(self._functions),
                    "bytes": self._function_bytes,
                    "puts": self._function_puts,
                    "evictions": self._function_evictions}

    # ------------------------------------------------------------ head stats
    def rpc_gcs_stats(self, conn, req_id, payload):
        """Control-plane observability in one call: lease/fencing state,
        snapshot counters, broadcast (full vs delta) accounting, and the
        last promotion record — the numbers the HA metrics export
        (`ray_tpu_head_failovers_total`, `ray_tpu_head_promotion_seconds`,
        `ray_tpu_fencing_rejections_total`) are derived from."""
        with self._lock:
            alive = sum(1 for n in self._nodes.values() if n["alive"])
            provisional = sum(1 for n in self._nodes.values()
                              if n["alive"] and n.get("restored"))
            # storage failure-domain roll-up: per-node object_store blocks
            # (heartbeat node_stats) summed fleet-wide + the degraded list
            storage = {"used_bytes": 0, "capacity_bytes": 0,
                       "pinned_bytes": 0, "pool_bytes": 0,
                       "spilled_bytes": 0, "nodes_reporting": 0,
                       "nodes_spill_degraded": []}
            for nid, n in self._nodes.items():
                blk = (n.get("stats") or {}).get("object_store")
                if not n["alive"] or not blk:
                    continue
                storage["nodes_reporting"] += 1
                for k in ("used_bytes", "capacity_bytes", "pinned_bytes",
                          "pool_bytes", "spilled_bytes"):
                    storage[k] += blk.get(k, 0)
                if blk.get("spill_degraded"):
                    storage["nodes_spill_degraded"].append(nid.hex())
            bcast = {"seq": self._bcast_seq, "fulls": self._bcast_fulls,
                     "deltas": self._bcast_deltas,
                     "bytes_sent": self._bcast_bytes,
                     "delta_enabled":
                         get_config().resource_broadcast_delta_enabled}
            joins = list(self._warm_lease_joins)
            # observability plane: span shipping + per-stage critical-path
            # latency roll-up (submit/lease/dispatch/run/result-deliver)
            from ray_tpu.util.stats import percentile as _pct

            stage_lat = {}
            for stage, window in self._stage_lat_us.items():
                vals = sorted(window)
                stage_lat[stage] = {
                    "count": len(vals),
                    "p50_us": round(_pct(vals, 0.50) or 0.0, 1),
                    "p99_us": round(_pct(vals, 0.99) or 0.0, 1),
                }
            tracing_blk = {
                "enabled": get_config().tracing_enabled,
                "traces": len(self._traces),
                "traces_evicted": self._traces_evicted,
                "spans_buffered": len(self._profile_events),
                "spans_dropped": self._spans_dropped,
                "clock_sources": len(self._span_clock_offsets),
                "stage_latency_us": stage_lat,
            }
            node_failure = {
                "deaths_by_reason": dict(self._node_deaths),
                "deaths_total": sum(self._node_deaths.values()),
                "drains_total": self._node_drains,
                "autoscaler": dict(self._autoscaler_stats),
                "pending_actor_restarts": len(self._pending_restarts),
                # partition failure domain: incarnation fences, gray-failure
                # quarantine state machine, stale-incarnation rejections
                # (the gcs_stats face of ray_tpu_node_fenced_total /
                # ray_tpu_node_quarantines_total /
                # ray_tpu_stale_incarnation_rejections_total)
                "fences_total": self._node_fences,
                "quarantines_total": self._node_quarantines,
                "quarantine_recoveries_total": self._quarantine_recoveries,
                "nodes_quarantined": sum(
                    1 for n in self._nodes.values()
                    if n["alive"] and n.get("quarantined")),
                "stale_incarnation_rejections": dict(self._stale_rejections),
                "stale_incarnation_rejections_total": sum(
                    self._stale_rejections.values()),
                "hot_env_keys": [e["env_key"]
                                 for e in self._hot_envs_payload_locked()],
                "warm_lease_joins": joins[-10:],
                "node_join_to_first_warm_lease_s":
                    joins[-1]["join_to_first_warm_lease_s"] if joins
                    else None,
            }
            # job failure domain: per-job live-actor roll-up + fate-sharing
            # reap counters (the gcs_stats face of ray_tpu_job_reaps_total;
            # `ray_tpu jobs` renders this block)
            live_actors: Dict[bytes, int] = {}
            detached_actors: Dict[bytes, int] = {}
            for aid, spec in self._actor_specs.items():
                info = self._actors.get(aid)
                if info is None or info.state == ActorState.DEAD:
                    continue
                sj = getattr(spec, "job_id", None)
                sjb = sj.binary() if hasattr(sj, "binary") else sj
                if sjb is None:
                    continue
                # live_actors counts EVERY non-dead actor of the job;
                # detached_actors is the subset a reap would spare, so
                # live - detached == what fate-sharing still owes the reaper
                live_actors[sjb] = live_actors.get(sjb, 0) + 1
                if getattr(spec, "lifetime", "non_detached") == "detached":
                    detached_actors[sjb] = detached_actors.get(sjb, 0) + 1
            jobs_blk = []
            for jid, j in self._jobs.items():
                jobs_blk.append({
                    "job_id": jid.hex() if isinstance(jid, bytes) else str(jid),
                    "status": j.get("status"),
                    "driver_address": j.get("driver_address", ""),
                    "start_time": j.get("start_time"),
                    "end_time": j.get("end_time"),
                    "death_cause": j.get("death_cause"),
                    "live_actors": live_actors.get(jid, 0),
                    "detached_actors": detached_actors.get(jid, 0),
                    "reap": j.get("reap"),
                })
            job_failure = dict(self._job_reap_stats)
            job_failure["jobs_tracked"] = len(self._jobs)
            job_failure["jobs_running"] = sum(
                1 for j in self._jobs.values()
                if j.get("status") == "RUNNING")
        return {
            "address": self._server.address,
            "session_id": self.session_id,
            "fence_epoch": self.fence_epoch,
            "fenced": self._fenced.is_set(),
            "lease_ttl_s": self._lease.ttl_s if self._lease else None,
            "nodes_alive": alive,
            "nodes_provisional": provisional,
            "snapshots": {"written": self._snapshots_written,
                          "last_version": self._snapshot_last_version,
                          "uri": self._snapshot_uri},
            "fencing_rejections": self._fencing_rejections,
            "broadcast": bcast,
            "node_failure": node_failure,
            "job_failure": job_failure,
            "jobs": jobs_blk,
            "storage": storage,
            "tracing": tracing_blk,
            "promotion": dict(self.promotion) if self.promotion else None,
        }

    # ---------------------------------------------------------------- jobs
    def rpc_register_job(self, conn, req_id, payload):
        job_id = payload["job_id"]
        with self._lock:
            self._dirty = True
            existing = self._jobs.get(job_id)
            if existing is not None:
                # re-registration: a driver reconnecting after a head
                # failover (its job may have been flipped FAILED at
                # snapshot restore) or after a conn blip. Revive it —
                # liveness comes from the driver itself, not the table.
                existing["status"] = "RUNNING"
                existing.pop("end_time", None)
                existing["driver_address"] = payload.get(
                    "driver_address", existing.get("driver_address", ""))
            else:
                self._jobs[job_id] = {
                    "job_id": job_id,
                    "driver_address": payload.get("driver_address", ""),
                    "start_time": time.time(),
                    "status": "RUNNING",
                }
            # adopt THIS conn as the driver's identity; any older conn's
            # close hook is superseded and must not reap
            self._job_conns[job_id] = id(conn)
            self._job_probe_after.pop(job_id, None)
            self._restored_unreaped.pop(job_id, None)
        conn.on_close.append(
            lambda c, jid=job_id: self._on_driver_conn_close(jid, id(c)))
        return True

    def rpc_mark_job_finished(self, conn, req_id, payload):
        with self._lock:
            j = self._jobs.get(payload["job_id"])
            if j:
                j["status"] = payload.get("status", "SUCCEEDED")
                j["end_time"] = time.time()
                self._dirty = True
                # clean exit: the later conn close finds status != RUNNING
                # and does nothing — finished jobs are NOT reaped (their
                # detached AND non-detached actors keep today's semantics)
                self._job_conns.pop(payload["job_id"], None)
        return True

    def rpc_get_jobs(self, conn, req_id, payload):
        with self._lock:
            return list(self._jobs.values())

    # ----------------------------- driver-death fate-sharing (job reap)
    def _on_driver_conn_close(self, job_id: bytes, conn_id: int) -> None:
        with self._lock:
            if self._job_conns.get(job_id) != conn_id:
                return  # superseded by a reconnect: not the live driver
            self._job_conns.pop(job_id, None)
            j = self._jobs.get(job_id)
            if j is None or j.get("status") != "RUNNING":
                return  # clean exit already marked finished
            addr = j.get("driver_address", "")
        # Conn loss is not proof of death (a blip severs the socket while
        # the driver lives and reconnects). Probe the driver's own RPC
        # server: refused -> the process is gone, reap now; accepting ->
        # arm the health-loop backstop and let re-registration cancel it.
        if self._driver_alive(addr):
            with self._lock:
                self._job_probe_after[job_id] = (
                    time.monotonic()
                    + get_config().job_reap_detection_bound_s)
            return
        # reap OFF the RPC loop: it fans out calls to every raylet
        threading.Thread(
            target=self._fail_and_reap_job,
            args=(job_id, "driver connection closed"),
            name="gcs-job-reap", daemon=True).start()

    @staticmethod
    def _driver_alive(address: str) -> bool:
        """Cheap liveness probe of the driver's worker RPC server: a bare
        TCP connect. A dead process's port refuses; a live driver's server
        accepts even while its GCS conn is severed."""
        if not address:
            return False
        host, _, port = address.rpartition(":")
        try:
            s = socket.create_connection((host, int(port)), timeout=1.0)
            s.close()
            return True
        except (OSError, ValueError):
            return False

    def _maybe_probe_dead_drivers(self, now: float) -> None:
        """Health-loop backstop: RUNNING jobs with no live driver conn
        (close hook lost with an old head, blip-severed socket) and
        snapshot-restored jobs flipped FAILED get their driver probed
        within job_reap_detection_bound_s; dead ones are reaped."""
        bound = get_config().job_reap_detection_bound_s
        due = []
        with self._lock:
            for jid, j in self._jobs.items():
                running = j.get("status") == "RUNNING"
                restored = jid in self._restored_unreaped
                if not (running or restored):
                    continue
                if running and jid in self._job_conns:
                    continue  # live conn: the close hook covers it
                after = self._job_probe_after.get(jid)
                if after is None:
                    self._job_probe_after[jid] = now + bound
                elif now >= after:
                    due.append((jid, j.get("driver_address", "")))
        for jid, addr in due:
            if self._driver_alive(addr):
                # alive but not (re-)registered yet — replay in progress
                # or a long blip; keep probing, never reap a live driver
                with self._lock:
                    self._job_probe_after[jid] = now + bound
                continue
            self._fail_and_reap_job(jid, "driver unreachable")

    def _fail_and_reap_job(self, job_id: bytes, cause: str) -> None:
        with self._lock:
            j = self._jobs.get(job_id)
            if j is None:
                return
            if j.get("status") != "RUNNING" \
                    and job_id not in self._restored_unreaped:
                return
            self._restored_unreaped.pop(job_id, None)
            self._job_probe_after.pop(job_id, None)
            self._job_conns.pop(job_id, None)
            j["status"] = "DEAD"
            j.setdefault("end_time", time.time())
            j["death_cause"] = cause
            self._dirty = True
        logger.warning("job %s driver died (%s); reaping its actors, "
                       "tasks, leases and objects", job_id.hex()[:8], cause)
        self._reap_job(job_id, cause)

    def _reap_job(self, job_id: bytes, cause: str) -> None:
        """Fate-sharing sweep for a dead job: kill its non-detached actors
        (detached ones are GCS-owned and survive), call reap_job on every
        alive raylet (queued-task purge, worker kills, lease/demand
        release, owned-object drop), and free function exports the job was
        the last owner of. Counters land in gcs_stats.job_failure and
        ray_tpu_job_reaps_total."""
        pacing = get_config().job_reap_pacing_ms / 1000.0
        with self._lock:
            doomed, spared = [], 0
            for aid, spec in list(self._actor_specs.items()):
                sj = getattr(spec, "job_id", None)
                sjb = sj.binary() if hasattr(sj, "binary") else sj
                if sjb != job_id:
                    continue
                if getattr(spec, "lifetime", "non_detached") == "detached":
                    spared += 1
                    continue
                info = self._actors.get(aid)
                if info is None or info.state == ActorState.DEAD:
                    continue
                doomed.append(aid)
            node_ids = [nid for nid, n in self._nodes.items()
                        if n.get("alive")]
        for aid in doomed:
            self._kill_actor_for_reap(aid, cause)
            if pacing:
                time.sleep(pacing)
        totals = {"queued_cancelled": 0, "workers_killed": 0,
                  "objects_dropped": 0, "bytes_dropped": 0}
        for nid in node_ids:
            client = self._raylet_client(nid)
            if client is None:
                continue
            try:
                r = client.call("reap_job", {"job_id": job_id}, timeout=10)
            except (OSError, TimeoutError, rpc.RpcCallError,
                    rpc.RpcDisconnected) as e:
                logger.info("reap_job on raylet %s failed: %s",
                            nid.hex()[:8], e)
                continue
            for k in totals:
                totals[k] += (r or {}).get(k, 0)
            if pacing:
                time.sleep(pacing)
        freed = 0
        with self._lock:
            # exports still referenced by a SURVIVING actor's creation spec
            # (a spared detached actor, another job's actor) must outlive
            # the job: a later restart resolves its class through them
            keep_fids = set()
            for aid, spec in self._actor_specs.items():
                info = self._actors.get(aid)
                if info is None or info.state == ActorState.DEAD:
                    continue
                fid = getattr(spec, "class_fn_id", None)
                if fid is not None:
                    keep_fids.add(fid)
            for fid, jobs in list(self._function_jobs.items()):
                jobs.discard(job_id)
                if jobs or fid in keep_fids:
                    continue
                self._function_jobs.pop(fid, None)
                blob = self._functions.pop(fid, None)
                if blob is not None:
                    self._function_bytes -= len(blob)
                    freed += 1
                    self._dirty = True
            st = self._job_reap_stats
            st["jobs_reaped"] += 1
            st["actors_killed"] += len(doomed)
            st["detached_spared"] += spared
            st["functions_freed"] += freed
            for k, v in totals.items():
                st[k] += v
            j = self._jobs.get(job_id)
            if j is not None:
                j["reap"] = {"actors_killed": len(doomed),
                             "detached_spared": spared,
                             "functions_freed": freed, **totals}
                self._dirty = True
        try:
            _job_metrics()["reaps"].inc()
        except Exception:
            pass
        logger.warning(
            "job %s reaped: %d actors killed (%d detached spared), %d "
            "queued tasks cancelled, %d workers killed, %d objects "
            "(%d bytes) dropped, %d function exports freed",
            job_id.hex()[:8], len(doomed), spared,
            totals["queued_cancelled"], totals["workers_killed"],
            totals["objects_dropped"], totals["bytes_dropped"], freed)

    def _kill_actor_for_reap(self, actor_id: ActorID, cause: str) -> None:
        """rpc_kill_actor's no-restart path, with an owner-died death
        cause: exhaust the budget, notify the hosting raylet, publish
        DEAD so in-flight callers fail typed instead of hanging."""
        death_cause = f"owner job died: {cause}"
        with self._lock:
            info = self._actors.get(actor_id)
            if info is None or info.state == ActorState.DEAD:
                return
            info.max_restarts = info.num_restarts  # exhaust budget
            info.state = ActorState.DEAD
            info.death_cause = death_cause
            node_id = info.node_id
            info.address = ""
            self._awaiting_rereg.pop(actor_id, None)
            self._dirty = True
            client = self._raylet_clients.get(node_id) if node_id else None
        if client is not None:
            try:
                client.notify("kill_actor_worker", {"actor_id": actor_id})
            except OSError as e:
                logger.debug("reap kill_actor notify to dead raylet: %s", e)
        self._publish(CH_ACTORS, {"actor_id": actor_id, "state": "DEAD",
                                  "address": "",
                                  "death_cause": death_cause})

    # ------------------------------------------------------------ task events
    def _ingest_task_event(self, payload) -> None:
        """Caller holds self._lock. One task lifecycle record into the ring."""
        key = payload["task_id"]
        e = self._task_events.get(key)
        if e is None:
            if len(self._task_events_order) >= self._max_task_events:
                old = self._task_events_order.pop(0)
                self._task_events.pop(old, None)
                # surfaced by list_task_events so `ray_tpu list tasks`
                # can SAY history was truncated instead of silently
                # showing a complete-looking window
                self._task_events_dropped += 1
            e = {"task_id": key}
            self._task_events[key] = e
            self._task_events_order.append(key)
        state = payload.get("state")
        # Count each task's SUBMITTED once per live entry. Batched buffers
        # mean a worker's RUNNING can now land before the driver's
        # SUBMITTED, so the count keys on a per-entry flag rather than on
        # entry creation; a terminal event recreating an evicted entry
        # (>10k tasks in flight) still can't inflate the running total, or
        # the derived pending count (submitted - finished - failed) would
        # drift upward forever.
        if state == "SUBMITTED" and not e.get("_counted_submitted"):
            e["_counted_submitted"] = True
            self._task_counts["submitted"] += 1
        if e.get("_terminal") and state not in ("FINISHED", "FAILED"):
            # A non-terminal event arriving AFTER the terminal one (e.g.
            # the driver's buffered SUBMITTED flushing behind the worker's
            # FINISHED) is recorded in the history but must not regress the
            # displayed state — no further event would ever repair it.
            e.setdefault("events", []).append((state or "?", time.time()))
            return
        e.update({k: v for k, v in payload.items() if k != "task_id"})
        e.setdefault("events", []).append((state or "?", time.time()))
        # running totals survive the event-window eviction above (the
        # dashboard's _total series must not saturate at the window)
        if state in ("FINISHED", "FAILED") and not e.get("_terminal"):
            e["_terminal"] = True
            self._task_counts[state.lower()] += 1

    def rpc_task_event(self, conn, req_id, payload):
        """Best-effort single task lifecycle record (legacy per-event wire
        format; in-tree emitters batch via task_events_batch)."""
        with self._lock:
            self._ingest_task_event(payload)
        return True

    def rpc_task_events_batch(self, conn, req_id, payload):
        """One worker-side TaskEventBuffer flush (reference
        TaskEventBuffer -> GcsTaskManager): a batch of task-state
        transitions, the emitter's dropped-event count, and any tracing
        spans recorded since its last flush — one notify per interval per
        process instead of one per transition."""
        with self._lock:
            for ev in payload.get("events", ()):
                self._ingest_task_event(ev)
            # events the WORKER dropped (its bounded buffer overflowed) are
            # history lost forever, same class as our ring eviction
            self._task_events_dropped += int(payload.get("dropped", 0))
            # spans the worker's tracing ring dropped: same honesty
            # contract for the timeline (surfaced in gcs_stats)
            self._spans_dropped += int(payload.get("spans_dropped", 0))
            src = payload.get("src")
            offset = payload.get("clock_offset_us")
            if src and offset is not None:
                self._span_clock_offsets[src] = float(offset)
            profile = payload.get("profile_events")
            if profile:
                self._append_profile_events(profile)
        return True

    def rpc_list_task_events(self, conn, req_id, payload):
        limit = (payload or {}).get("limit", 1000)
        if limit <= 0:
            return []
        with self._lock:
            keys = self._task_events_order[-limit:]
            # underscore keys (_terminal, _counted_submitted) are GCS
            # bookkeeping, not part of the listing surface
            out = [{f: v for f, v in self._task_events[k].items()
                    if not f.startswith("_")} for k in keys]
            dropped = self._task_events_dropped
        if dropped:
            # sideband metadata row: EVICTED history is gone forever —
            # distinct from limit windowing, where a larger limit still
            # reaches the older retained entries. The row counts against
            # the limit so consumers never receive more than they asked.
            if len(out) >= limit:
                out = out[1:]
            out.append({"__truncated__": dropped})
        return out

    # stages of the per-task critical path (span categories); each keeps a
    # bounded latency window for the p50/p99 roll-up in gcs_stats
    _TRACE_STAGES = ("task_submit", "task_lease", "task_dispatch",
                     "task_execution", "task_result")
    _STAGE_WINDOW = 10_000

    def _append_profile_events(self, events) -> None:
        """Caller holds self._lock. Capped ring so the GCS can't grow
        unboundedly. Spans carrying a trace_id additionally index into the
        per-trace ring (whole-trace eviction, oldest first) and feed the
        per-stage latency windows."""
        self._profile_events.extend(events)
        if len(self._profile_events) > 100_000:
            self._profile_events = self._profile_events[-100_000:]
        max_traces = max(1, get_config().tracing_max_traces)
        for e in events:
            tid = e.get("trace_id")
            if tid:
                spans = self._traces.get(tid)
                if spans is None:
                    while len(self._traces) >= max_traces:
                        self._traces.popitem(last=False)
                        self._traces_evicted += 1
                    spans = self._traces[tid] = []
                spans.append(e)
            cat = e.get("cat")
            if cat in self._TRACE_STAGES and "dur" in e:
                window = self._stage_lat_us.setdefault(cat, [])
                window.append(float(e["dur"]))
                if len(window) > self._STAGE_WINDOW:
                    del window[:len(window) - self._STAGE_WINDOW]

    def rpc_profile_events(self, conn, req_id, payload):
        """Chrome-trace spans shipped by workers (reference ProfileEvent
        buffer; legacy per-flush wire format — in-tree emitters batch via
        task_events_batch)."""
        with self._lock:
            self._append_profile_events(payload.get("events", []))
        return True

    def rpc_get_profile_events(self, conn, req_id, payload):
        with self._lock:
            return list(self._profile_events)

    # ------------------------------------------------------------- tracing
    def rpc_clock_probe(self, conn, req_id, payload):
        """Server-side wall stamp for NTP-style offset estimation: the
        caller brackets this call with local stamps t0/t2 and computes
        offset = t1 - (t0 + t2) / 2 (task_events.py). The GCS clock is the
        fleet's reference frame for merged timelines."""
        return {"t1_us": time.time() * 1e6}

    def rpc_get_span_offsets(self, conn, req_id, payload):
        """Per-source clock offsets (src hex -> offset_us vs this GCS),
        applied at merge time to align spans from different nodes."""
        with self._lock:
            return dict(self._span_clock_offsets)

    def rpc_get_trace(self, conn, req_id, payload):
        """Spans of one causal tree, by trace_id or by task_id (any span
        whose trace contains the task). Returns spans + the offsets needed
        to align them."""
        payload = payload or {}
        trace_id = payload.get("trace_id")
        task_id = payload.get("task_id")
        with self._lock:
            spans: List[dict] = []
            if trace_id:
                spans = list(self._traces.get(trace_id, ()))
            elif task_id:
                for tid, tspans in self._traces.items():
                    if any((s.get("args") or {}).get("task_id") == task_id
                           for s in tspans):
                        trace_id = tid
                        spans = list(tspans)
                        break
            return {"trace_id": trace_id, "spans": spans,
                    "offsets": dict(self._span_clock_offsets)}

    def rpc_list_traces(self, conn, req_id, payload):
        """Newest-first trace summaries for `ray_tpu timeline --trace`
        discovery."""
        limit = (payload or {}).get("limit", 50)
        with self._lock:
            items = list(self._traces.items())[-limit:]
        out = []
        for tid, spans in reversed(items):
            ts = [s.get("ts", 0) for s in spans]
            out.append({"trace_id": tid, "spans": len(spans),
                        "first_ts_us": min(ts) if ts else 0,
                        "last_ts_us": max(ts) if ts else 0})
        return out

    def rpc_task_counts(self, conn, req_id, payload):
        """Cumulative task totals (unwindowed, unlike list_task_events)."""
        with self._lock:
            c = dict(self._task_counts)
        c["pending"] = max(0, c["submitted"] - c["finished"] - c["failed"])
        return c

    # ---------------------------------------------------------------- actors
    def rpc_register_actor(self, conn, req_id, payload):
        """Register + schedule an actor (cf. gcs_actor_manager.cc:246,271)."""
        spec: ActorCreationSpec = payload["spec"]
        owner_address: str = payload.get("owner_address", "")
        with self._lock:
            # Idempotent: a retried register (reconnecting client re-sending
            # after the reply was lost in a GCS crash) must not schedule a
            # second worker for the same actor id.
            existing_info = self._actors.get(spec.actor_id)
            if existing_info is not None and existing_info.state != ActorState.DEAD:
                return {"ok": True}
            if spec.name:
                key = (spec.namespace, spec.name)
                if key in self._named_actors:
                    existing = self._named_actors[key]
                    if self._actors[existing].state != ActorState.DEAD:
                        return {"error": f"actor name '{spec.name}' already taken"}
                self._named_actors[key] = spec.actor_id
            info = ActorInfo(
                actor_id=spec.actor_id,
                name=spec.name,
                namespace=spec.namespace,
                state=ActorState.PENDING,
                max_restarts=spec.max_restarts,
                class_name=payload.get("class_name", ""),
            )
            self._actors[spec.actor_id] = info
            self._actor_specs[spec.actor_id] = spec
            self._actor_owners[spec.actor_id] = owner_address
            self._dirty = True
        ok = self._schedule_actor(spec.actor_id)
        if not ok:
            err = (f"no feasible node for actor resources {spec.resources} "
                   f"(cluster: {self.cluster_view()})")
            with self._lock:
                info = self._actors[spec.actor_id]
                info.state = ActorState.DEAD
                info.death_cause = err
            self._publish(CH_ACTORS, {"actor_id": spec.actor_id, "state": "DEAD",
                                      "address": "", "death_cause": err})
            return {"error": err}
        return {"ok": True}

    def _schedule_actor(self, actor_id: ActorID,
                        require_available: bool = False) -> bool:
        """Pick a node for the actor and ask its raylet to create it
        (cf. GcsActorScheduler::Schedule, gcs_actor_scheduler.cc:49).

        `require_available=True` (the RESTART path) only accepts nodes that
        can hold the actor's demand NOW: a restart after node death must
        land on a surviving node with capacity or WAIT for the autoscaler's
        replacement (pending-restart retry) — queuing it on a full survivor
        would strand it behind capacity that may never free."""
        with self._lock:
            spec = self._actor_specs.get(actor_id)
            if spec is None:
                # Snapshot-restored actor whose spec didn't survive and whose
                # worker never re-registered: nothing to schedule from.
                return False
            views = [
                NodeView(nid, n["resources_total"], n["resources_available"], n["labels"])
                for nid, n in self._nodes.items()
                if n["alive"] and not n.get("quarantined")
            ]
        if require_available and spec.scheduling.placement_group_id is None:
            views = [v for v in views if v.is_available(spec.resources)]
        target = self._policy.select_node(views, spec.resources, spec.scheduling, prefer_node=None,
                                          pg_table=self._pgs)
        if target is None:
            return False
        if require_available:
            # PG-routed restarts come back as the bundle's node: reject a
            # dead one (its bundle is awaiting reschedule) instead of
            # dispatching into the void
            with self._lock:
                n = self._nodes.get(target)
                if n is None or not n.get("alive"):
                    return False
        with self._lock:
            info = self._actors[actor_id]
            info.node_id = target
            # the actor's restart count IS its incarnation: the hosting
            # worker learns it here and stamps every reply with it, and
            # handles refuse to let a superseded instance service a call —
            # exactly-one-live-instance across a partition heal
            spec.incarnation = info.num_restarts
            # optimistic charge of the head's resource view: without it a
            # burst of creations all reads the same stale availability and
            # piles onto one node (the raylet's charge only flows back on
            # its next debounced report). The raylet's reports overwrite
            # the view wholesale, so this converges to truth either way.
            if spec.scheduling.placement_group_id is None:
                n = self._nodes.get(target)
                if n is not None:
                    avail = n["resources_available"]
                    for r, q in spec.resources.items():
                        avail[r] = avail.get(r, 0.0) - q
                    self._bcast_dirty.add(target.hex())
        client = self._raylet_client(target)
        if client is None:
            return False
        try:
            client.notify("create_actor", {"spec": spec})
        except Exception:
            logger.exception("failed to dispatch actor creation to %s", target.hex()[:8])
            return False
        self._note_first_schedule()
        return True

    def rpc_actor_creation_done(self, conn, req_id, payload):
        actor_id = payload["actor_id"]
        with self._lock:
            info = self._actors.get(actor_id)
            if info is not None and payload.get("success", True):
                done_inc = payload.get("incarnation")
                if done_inc is not None and done_inc < info.num_restarts:
                    # a SUPERSEDED dispatch completing late (the node it
                    # went to was partitioned/declared dead and the actor
                    # was restarted elsewhere): marking ALIVE at its
                    # address would resurrect the zombie instance — reject
                    # and kill the stale worker instead
                    stale_node = payload.get("node_id")
                    kill_client = self._raylet_clients.get(stale_node) \
                        if stale_node else None
                    logger.warning(
                        "rejecting stale actor_creation_done for %s "
                        "(incarnation %s < current %s)", actor_id,
                        done_inc, info.num_restarts)
                else:
                    kill_client = "accept"
            else:
                kill_client = "accept"
        if kill_client != "accept":
            self._count_stale("actor_creation_done")
            if kill_client is not None:
                try:
                    kill_client.notify("kill_actor_worker",
                                       {"actor_id": actor_id})
                except OSError:
                    pass
            return False
        with self._lock:
            info = self._actors.get(actor_id)
            if info is None:
                spec: Optional[ActorCreationSpec] = payload.get("spec")
                if spec is None or not payload.get("success", True):
                    return False
                # The GCS restarted between dispatching this creation and its
                # completion: rebuild the record from the worker's spec so
                # the actor still becomes ALIVE.
                info = ActorInfo(
                    actor_id=actor_id, name=spec.name,
                    namespace=spec.namespace, state=ActorState.PENDING,
                    max_restarts=spec.max_restarts, class_name="")
                self._actors[actor_id] = info
                self._actor_specs[actor_id] = spec
                if spec.name:
                    self._named_actors[(spec.namespace, spec.name)] = actor_id
            if payload.get("success", True):
                n = self._nodes.get(payload["node_id"])
                if n is not None and not n.get("alive", True):
                    # success racing the node's death (the creation landed,
                    # then the node was killed): the address is a corpse —
                    # keep the actor RESTARTING and let the paced retry
                    # place it on live capacity instead. An UNKNOWN node
                    # stays on the ALIVE path: after a GCS restart the
                    # done can beat the node's re-registration, and
                    # re-parking then would double-create the actor.
                    info.state = ActorState.RESTARTING
                    info.address = ""
                    info.node_id = payload["node_id"]
                    self._pending_restarts[actor_id] = 0.0
                    self._pending_restart_since.setdefault(
                        actor_id, time.monotonic())
                    self._dirty = True
                    logger.warning("actor %s creation reported from dead "
                                   "node %s; re-parking for retry",
                                   actor_id, payload["node_id"].hex()[:8])
                else:
                    info.state = ActorState.ALIVE
                    info.address = payload["address"]
                    info.node_id = payload["node_id"]
                    self._pending_restarts.pop(actor_id, None)
                    self._pending_restart_since.pop(actor_id, None)
            else:
                info.state = ActorState.DEAD
                info.death_cause = payload.get("error", "creation failed")
            self._dirty = True
        self._publish(CH_ACTORS, {"actor_id": actor_id, "state": info.state.value,
                                  "address": info.address, "death_cause": info.death_cause,
                                  "incarnation": info.num_restarts})
        return True

    def rpc_reregister_actor(self, conn, req_id, payload):
        """A live actor worker re-announces itself after a GCS restart
        (reference: GCS rebuilds the actor table from Redis +
        resubscription; here the worker IS the source of truth). Restores
        the ALIVE record, the creation spec (so restart-on-failure still
        works) and the named-actor binding. Incarnation-fenced: a zombie
        instance (its actor was restarted elsewhere while its node was
        partitioned) re-announcing a SUPERSEDED incarnation is rejected
        with a typed fence reply — the worker exits instead of taking the
        record back from the live instance."""
        actor_id: ActorID = payload["actor_id"]
        spec: Optional[ActorCreationSpec] = payload.get("spec")
        offered = payload.get("incarnation")
        with self._lock:
            info = self._actors.get(actor_id)
            stale = (info is not None and offered is not None
                     and (offered < info.num_restarts
                          or (info.state == ActorState.ALIVE
                              and offered == info.num_restarts
                              and info.address
                              and info.address != payload["address"])))
        if stale:
            self._count_stale("reregister_actor")
            logger.warning(
                "rejecting reregister of actor %s from %s: incarnation %s "
                "superseded (current %s at %s)", actor_id,
                payload["address"], offered, info.num_restarts, info.address)
            return {"fenced": True,
                    "reason": "actor incarnation superseded"}
        with self._lock:
            info = self._actors.get(actor_id)
            if info is None:
                # No snapshot record: rebuild identity from the spec. The
                # restart budget (num_restarts) is preserved whenever the
                # snapshot had it — a GCS restart must not reset it.
                info = ActorInfo(
                    actor_id=actor_id,
                    name=spec.name if spec else None,
                    namespace=spec.namespace if spec else "",
                    state=ActorState.ALIVE,
                    max_restarts=spec.max_restarts if spec else 0,
                )
                self._actors[actor_id] = info
            info.state = ActorState.ALIVE
            info.address = payload["address"]
            info.node_id = payload.get("node_id")
            self._awaiting_rereg.pop(actor_id, None)
            self._pending_restarts.pop(actor_id, None)
            self._pending_restart_since.pop(actor_id, None)
            if spec is not None:
                self._actor_specs[actor_id] = spec
                if spec.name:
                    self._named_actors[(spec.namespace, spec.name)] = actor_id
            self._dirty = True
        self._publish(CH_ACTORS, {"actor_id": actor_id, "state": "ALIVE",
                                  "address": payload["address"],
                                  "death_cause": "",
                                  "incarnation": info.num_restarts})
        return True

    def rpc_actor_failed(self, conn, req_id, payload):
        """Worker-death report from a raylet. Node-scoped: a report from a
        node that no longer HOSTS the actor (a fenced zombie killing its
        superseded workers, a late report racing a restart) must not charge
        the budget or restart the live instance."""
        actor_id = payload["actor_id"]
        reporter = payload.get("node_id")
        if reporter is not None:
            with self._lock:
                info = self._actors.get(actor_id)
                mismatch = (info is not None and info.node_id is not None
                            and info.node_id != reporter)
            if mismatch:
                self._count_stale("actor_failed")
                logger.info(
                    "ignoring actor_failed for %s from node %s: actor is "
                    "hosted on %s", actor_id, reporter.hex()[:8],
                    info.node_id.hex()[:8])
                return False
        self._handle_actor_failure(actor_id, payload.get("reason", "worker died"))
        return True

    def _handle_actor_failure(self, actor_id: ActorID, reason: str) -> None:
        """Restart budget logic (cf. gcs_actor_manager.cc:1149 reschedule)."""
        with self._lock:
            info = self._actors.get(actor_id)
            if info is None or info.state == ActorState.DEAD:
                return
            can_restart = info.max_restarts == -1 or info.num_restarts < info.max_restarts
            if can_restart:
                info.num_restarts += 1
                info.state = ActorState.RESTARTING
                info.address = ""
            else:
                info.state = ActorState.DEAD
                info.death_cause = reason
            self._dirty = True
        if info.state == ActorState.RESTARTING:
            self._publish(CH_ACTORS, {"actor_id": actor_id, "state": info.state.value,
                                      "address": "", "death_cause": ""})
            if not self._schedule_actor(actor_id, require_available=True):
                # No capacity RIGHT NOW (the actor's node just died and its
                # replacement hasn't joined): keep it RESTARTING and let the
                # paced health-loop retry land it on a surviving or
                # replacement node — killing it here would turn every
                # transient capacity dip into a permanent actor loss.
                with self._lock:
                    if info.state == ActorState.RESTARTING:
                        self._pending_restarts[actor_id] = time.monotonic() \
                            + self._RESTART_RETRY_INTERVAL_S
                        self._pending_restart_since.setdefault(
                            actor_id, time.monotonic())
                logger.info("actor %s restart has no feasible capacity yet; "
                            "queued for paced retry", actor_id)
        else:
            self._publish(CH_ACTORS, {"actor_id": actor_id, "state": info.state.value,
                                      "address": "", "death_cause": info.death_cause})

    def rpc_get_actor_info(self, conn, req_id, payload):
        with self._lock:
            if "name" in payload:
                aid = self._named_actors.get((payload.get("namespace", ""), payload["name"]))
                if aid is None:
                    return None
            else:
                aid = payload["actor_id"]
            info = self._actors.get(aid)
            if info is None:
                return None
            return {
                "actor_id": info.actor_id,
                "name": info.name,
                "state": info.state.value,
                "address": info.address,
                "node_id": info.node_id,
                "num_restarts": info.num_restarts,
                # the restart count doubles as the live incarnation: handles
                # pin calls to it so a superseded instance can never serve
                "incarnation": info.num_restarts,
                "death_cause": info.death_cause,
                "class_name": info.class_name,
            }

    def rpc_list_actors(self, conn, req_id, payload):
        with self._lock:
            return [
                {"actor_id": a.actor_id, "name": a.name, "state": a.state.value,
                 "address": a.address, "class_name": a.class_name,
                 "num_restarts": a.num_restarts}
                for a in self._actors.values()
            ]

    def rpc_kill_actor(self, conn, req_id, payload):
        actor_id = payload["actor_id"]
        no_restart = payload.get("no_restart", True)
        with self._lock:
            info = self._actors.get(actor_id)
            if info is None:
                return False
            node_id = info.node_id
            client = self._raylet_clients.get(node_id) if node_id else None
            if no_restart:
                info.max_restarts = info.num_restarts  # exhaust budget
                info.state = ActorState.DEAD
                info.death_cause = "killed via ray.kill()"
                info.address = ""
        if client is not None:
            try:
                client.notify("kill_actor_worker", {"actor_id": actor_id})
            except OSError as e:
                # the raylet hosting the actor is gone — the kill outcome
                # it was asked for has already happened
                logger.debug("kill_actor notify to dead raylet: %s", e)
        if no_restart:
            self._publish(CH_ACTORS, {"actor_id": actor_id, "state": "DEAD",
                                      "address": "", "death_cause": "killed via ray.kill()"})
        else:
            self._handle_actor_failure(actor_id, "killed via ray.kill(no_restart=False)")
        return True

    # ------------------------------------------------------------ placement
    def rpc_create_placement_group(self, conn, req_id, payload):
        """2-phase bundle reservation (cf. gcs_placement_group_scheduler.h),
        run OFF the RPC loop (prepare calls block) and replied via
        Deferred. Idempotent per pg_id: a client whose create call died
        with the old head re-sends it to the replacement, which either
        finds the PG already CREATED (snapshot/resume) or re-runs the
        protocol — raylet-side prepare_bundle is idempotent, so a bundle
        the old head already reserved is not double-charged."""
        threading.Thread(
            target=self._create_pg_and_reply,
            args=(conn, req_id, payload), name="gcs-pg-create",
            daemon=True).start()
        return rpc.RpcServer.DEFERRED

    def _create_pg_and_reply(self, conn, req_id, payload) -> None:
        try:
            result = self._create_placement_group(
                payload["pg_id"], payload["bundles"], payload["strategy"],
                payload.get("name"))
        except Exception as e:
            logger.exception("placement group creation failed")
            result = f"placement group creation failed: {e}"
            try:
                conn.reply(req_id, result, is_error=True)
            except (OSError, RuntimeError):
                pass  # head shutting down mid-creation; client will retry
            return
        try:
            conn.reply(req_id, result)
        except (OSError, RuntimeError):
            pass  # head shutting down mid-creation; client will retry

    def _create_placement_group(self, pg_id: PlacementGroupID,
                                bundles: List[Dict[str, float]],
                                strategy: str, name) -> dict:
        with self._pg_2pc_lock:
            with self._lock:
                existing = self._pgs.get(pg_id)
                if existing is not None and existing.get("state") == "CREATED":
                    return {"ok": True, "placement": existing["placement"]}
                # PREPARING is durable: if this head dies mid-protocol, its
                # replacement sees the marker and resumes or fails the PG
                # instead of leaving clients polling forever.
                self._pgs[pg_id] = {
                    "state": "PREPARING", "bundles": bundles,
                    "strategy": strategy, "name": name, "placement": None}
                self._dirty = True
                views = [
                    NodeView(nid, n["resources_total"], n["resources_available"], n["labels"])
                    for nid, n in self._nodes.items()
                    if n["alive"] and not n.get("quarantined")
                ]
            placement = self._policy.place_bundles(views, bundles, strategy)
            if placement is None:
                with self._lock:
                    self._pgs[pg_id].update(state="PENDING", placement=None)
                    self._dirty = True
                return {"ok": False, "error": "infeasible"}
            # Phase 1: prepare on each raylet; rollback on any failure.
            prepared = []
            ok = True
            for idx, node_id in enumerate(placement):
                client = self._raylet_client(node_id)
                if client is None:
                    ok = False
                    break
                try:
                    r = client.call("prepare_bundle", {
                        "pg_id": pg_id, "bundle_index": idx, "resources": bundles[idx]}, timeout=10)
                except (OSError, TimeoutError, rpc.RpcCallError,
                        rpc.RpcDisconnected) as e:
                    logger.info("prepare_bundle on %s failed: %s",
                                node_id.hex()[:8], e)
                    r = False
                if not r:
                    ok = False
                    break
                prepared.append((idx, node_id))
            if not ok:
                for idx, node_id in prepared:
                    c = self._raylet_client(node_id)
                    if c:
                        try:
                            c.notify("return_bundle", {"pg_id": pg_id, "bundle_index": idx})
                        except OSError as e:
                            logger.debug("return_bundle to dead raylet: %s", e)
                # PENDING is retryable: the paced health-loop retry re-runs
                # the 2PC, so a transient prepare failure (link blip, node
                # mid-death) heals instead of stranding the group
                with self._lock:
                    self._pgs[pg_id].update(state="PENDING", placement=None)
                    self._dirty = True
                return {"ok": False, "error": "prepare failed"}
            # Phase 2: commit. Tolerant per node: a raylet dying between
            # prepare and commit must not blow up the whole creation — its
            # uncommitted reservation returns via the 2PC orphan reaper and
            # the node-death path fails over whatever ran there.
            for idx, node_id in prepared:
                client = self._raylet_client(node_id)
                try:
                    if client is None:
                        raise OSError("raylet client gone")
                    client.notify("commit_bundle",
                                  {"pg_id": pg_id, "bundle_index": idx})
                except OSError as e:
                    logger.warning(
                        "commit_bundle (%s, %d) to %s lost: %s", pg_id, idx,
                        node_id.hex()[:8], e)
            with self._lock:
                self._pgs[pg_id] = {
                    "state": "CREATED", "bundles": bundles, "strategy": strategy,
                    "name": name, "placement": placement,
                }
                self._dirty = True
            return {"ok": True, "placement": placement}

    def rpc_get_placement_group(self, conn, req_id, payload):
        with self._lock:
            pg = self._pgs.get(payload["pg_id"])
            if pg is None and "name" in payload:
                for pid, p in self._pgs.items():
                    if p.get("name") == payload["name"]:
                        pg = dict(p); pg["pg_id"] = pid
                        break
            return pg

    def rpc_remove_placement_group(self, conn, req_id, payload):
        pg_id = payload["pg_id"]
        with self._lock:
            pg = self._pgs.pop(pg_id, None)
            self._dirty = self._dirty or pg is not None
        if pg and pg.get("placement"):
            for idx, node_id in enumerate(pg["placement"]):
                c = self._raylet_clients.get(node_id)
                if c:
                    try:
                        c.notify("return_bundle", {"pg_id": pg_id, "bundle_index": idx})
                    except OSError as e:
                        logger.debug("return_bundle to dead raylet: %s", e)
        return pg is not None

    def rpc_list_placement_groups(self, conn, req_id, payload):
        with self._lock:
            return [
                {"pg_id": pid, "state": p["state"], "strategy": p["strategy"],
                 "bundles": p["bundles"], "name": p.get("name"),
                 "placement": p.get("placement")}
                for pid, p in self._pgs.items()
            ]


class StandbyHead:
    """Warm standby GCS (ROADMAP item 5; Ray 2.x GCS fault-tolerance
    design): tails the `VersionedSnapshots` stream so its in-memory copy of
    the control-plane state is always ≤1 snapshot behind, watches the head
    lease, and when the lease EXPIRES (crash) or is RELINQUISHED (rolling
    upgrade, `GcsServer.drain_lease`) takes over via the lease-epoch CAS:

        acquire(expect_epoch=<the epoch we saw expire>) -> epoch+1

    Promotion then boots a `GcsServer` pre-seeded with the tailed payload
    (restore = one deserialize, no store walk) whose readopt pass dials the
    snapshot-known raylets with `promote_announce` — same-session raylets
    re-adopt in that one RPC, giving sub-second failover. The OLD head, if
    it revives, is fenced: its epoch trails the store's, so its snapshot
    saves raise and its announces are dropped.

    Run standalone with `ray_tpu start --standby --snapshot-uri ...`.
    """

    def __init__(self, snapshot_uri: str, host: str = "127.0.0.1",
                 port: int = 0):
        from ray_tpu.core.head_lease import HeadLease, new_owner_token
        from ray_tpu.core.snapshot_store import (VersionedSnapshots,
                                                 store_from_uri)

        self._uri = snapshot_uri
        self._host = host
        self._port = port
        store = store_from_uri(snapshot_uri)
        self._snaps = VersionedSnapshots(
            store, prefix="gcs", keep=get_config().gcs_snapshot_keep)
        self._lease = HeadLease(store)
        self._owner = new_owner_token()
        self._tailed: Optional[bytes] = None
        self._tailed_version = 0
        self._tailed_epoch = 0  # fence_epoch persisted in the tailed payload
        self._seen_epoch = 0
        self._stop_evt = threading.Event()
        self._promoted_evt = threading.Event()
        self._promoted: Optional[GcsServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "StandbyHead":
        self._thread = threading.Thread(target=self._run, name="gcs-standby",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop tailing. Does NOT stop a promoted GcsServer — once promoted
        it is the cluster's head and owns its own lifecycle."""
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    @property
    def promoted(self) -> Optional[GcsServer]:
        return self._promoted

    def wait_promoted(self, timeout: Optional[float] = None
                      ) -> Optional[GcsServer]:
        self._promoted_evt.wait(timeout)
        return self._promoted

    def stats(self) -> dict:
        return {"tailed_version": self._tailed_version,
                "seen_epoch": self._seen_epoch,
                "promoted": self._promoted is not None,
                "snapshot_uri": self._uri}

    # ------------------------------------------------------------- tail loop
    def _run(self) -> None:
        from ray_tpu.core.head_lease import LeaseHeldError, LeaseLostError

        cfg = get_config()
        poll = cfg.head_standby_poll_s or max(
            0.05, cfg.head_lease_ttl_s / 4.0)
        while not self._stop_evt.wait(poll):
            try:
                self._tail_once()
            except Exception:
                logger.exception("standby snapshot tail failed")
            try:
                rec = self._lease.read()
            except Exception:
                logger.exception("standby lease read failed")
                continue
            if rec is None:
                # no head has ever claimed the lease; without a snapshot
                # there is nothing to take over — stay standby
                continue
            self._seen_epoch = max(self._seen_epoch, int(rec.get("epoch", 0)))
            if rec.get("expires_at", 0.0) > time.time():
                continue
            # expired/relinquished: claim it. expect_epoch pins the CAS to
            # the epoch we SAW expire — a head that renewed (or another
            # standby that won) in the window refuses us — and the floor
            # (highest epoch seen on the lease OR in the snapshot stream)
            # stops a torn lease record from resetting the epoch under the
            # fleet.
            try:
                epoch = self._lease.acquire(
                    self._owner, expect_epoch=rec["epoch"],
                    floor=max(self._seen_epoch, self._tailed_epoch) + 1)
            except (LeaseHeldError, LeaseLostError) as e:
                logger.info("standby promotion attempt refused: %s", e)
                continue
            try:
                self._promote(epoch, old_lease=rec)
                return
            except Exception:
                # a failed boot (port taken, store error) with the epoch
                # already claimed would otherwise leave the cluster
                # HEADLESS: hand the lease back (expire-now at our epoch)
                # so another standby — or this loop's next pass — can claim
                # epoch+1, and keep tailing.
                logger.exception("promotion to epoch %d failed; "
                                 "relinquishing the lease and retrying",
                                 epoch)
                try:
                    self._lease.relinquish(self._owner, epoch)
                except Exception:
                    logger.exception("post-failure lease relinquish failed")
                self._seen_epoch = max(self._seen_epoch, epoch)

    def _tail_once(self) -> None:
        newest = self._snaps.latest_version()
        if newest <= self._tailed_version:
            return
        payload, version = self._snaps.load_latest_with_version()
        if payload is not None:
            self._tailed = payload
            self._tailed_version = version
            try:
                import pickle

                self._tailed_epoch = int(
                    pickle.loads(payload).get("fence_epoch", 0))
            except Exception:
                logger.debug("tailed snapshot carries no readable "
                             "fence_epoch", exc_info=True)

    def _promote(self, epoch: int, old_lease: dict) -> None:
        lease_expired_at = old_lease.get("expires_at")
        logger.warning("standby promoting to active head: epoch %d "
                       "(tailed snapshot v%d)", epoch, self._tailed_version)
        # one last tail: the dead head's final flush may have landed after
        # our previous poll
        try:
            self._tail_once()
        except Exception:
            logger.exception("pre-promotion tail failed; promoting from v%d",
                             self._tailed_version)
        gcs = GcsServer(
            host=self._host, port=self._port, snapshot_uri=self._uri,
            preloaded_snapshot=self._tailed,
            lease_grant={"owner": self._owner, "epoch": epoch,
                         "lease_expired_at": lease_expired_at,
                         "tailed_version": self._tailed_version})
        gcs.start()
        try:
            _head_metrics()["failovers"].inc()
        except Exception:
            pass
        self._fence_predecessor(old_lease, gcs)
        self._promoted = gcs
        self._promoted_evt.set()

    def _fence_predecessor(self, old_lease: dict, gcs: GcsServer) -> None:
        """Best-effort direct fence of a still-RUNNING predecessor (lease
        starved, process alive): dial the address its lease record carried
        and tell it the epoch moved on. Without this it self-fences on its
        next lease read anyway — this just collapses the stale-serving
        window to one RPC."""
        address = old_lease.get("address")
        if not address or address == gcs.address:
            return

        def run():
            try:
                client = rpc.connect_with_retry(address, timeout=2)
                try:
                    client.call("head_fenced",
                                {"epoch": gcs.fence_epoch,
                                 "address": gcs.address}, timeout=3)
                finally:
                    client.close()
            except Exception:
                logger.info("predecessor head at %s unreachable for direct "
                            "fence (already dead?)", address)

        threading.Thread(target=run, name="gcs-fence-predecessor",
                         daemon=True).start()
