"""Actor API: ActorClass / ActorHandle / ActorMethod.

Mirrors the reference's `python/ray/actor.py` surface (ActorClass:377,
ActorHandle:1022, ActorMethod:92): `@ray.remote` on a class yields an
ActorClass whose `.remote(...)` creates the actor via the control plane and
returns a handle; method calls submit actor tasks over the direct
worker-to-worker transport with per-caller ordering.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from ray_tpu.core.ids import ActorID
from ray_tpu.core.task_spec import ActorCreationSpec, SchedulingStrategy


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1,
                 concurrency_group: str = None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._name, args, kwargs,
                                    self._num_returns,
                                    self._concurrency_group)

    def options(self, num_returns: int = 1, concurrency_group: str = None):
        return ActorMethod(self._handle, self._name, num_returns,
                           concurrency_group)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor methods cannot be called directly; use "
            f"`actor.{self._name}.remote(...)`.")


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str = ""):
        self._actor_id = actor_id
        self._class_name = class_name

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def _invoke(self, method_name: str, args, kwargs, num_returns,
                concurrency_group=None):
        from ray_tpu.core.api import _global_worker

        if num_returns in ("dynamic", "streaming"):
            num_returns = -1  # generator method (reference num_returns="dynamic")
        w = _global_worker()
        refs = w.submit_actor_task(
            self._actor_id, method_name, args, kwargs, num_returns=num_returns,
            concurrency_group=concurrency_group)
        if num_returns == -1:
            return w.make_dynamic_generator(refs[0])
        return refs[0] if num_returns == 1 else refs

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name))


class ActorClass:
    def __init__(self, cls: type, default_options: Optional[dict] = None):
        self._cls = cls
        self._opts: Dict[str, Any] = dict(default_options or {})

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._opts)
        merged.update(opts)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu.core.api import _global_worker
        from ray_tpu.core import serialization

        w = _global_worker()
        o = self._opts
        resources = dict(o.get("resources") or {})
        if o.get("num_cpus") is not None:
            resources["CPU"] = float(o["num_cpus"])
        if o.get("num_tpus") is not None:
            resources["TPU"] = float(o["num_tpus"])
        if o.get("num_gpus") is not None:
            resources["GPU"] = float(o["num_gpus"])
        scheduling = o.get("scheduling_strategy")
        if scheduling is None:
            scheduling = SchedulingStrategy()
            pg = o.get("placement_group")
            if pg is not None:
                scheduling.placement_group_id = pg.id
                scheduling.bundle_index = o.get("placement_group_bundle_index", -1)

        # export-once class pickle (same fast lane as task functions):
        # repeated .remote() of one ActorClass ships a 16-byte id, and the
        # hosting worker resolves it through its deserialized-class LRU.
        # Client-mode workers have no function table — ship the blob and
        # let the server-side driver's spec pass through unchanged.
        ft = getattr(w, "function_table", None)
        if ft is not None:
            class_fn_id, class_blob = ft.export(self._cls)
        else:
            import cloudpickle

            class_fn_id, class_blob = None, cloudpickle.dumps(self._cls)
        spec = ActorCreationSpec(
            actor_id=ActorID.from_random(),
            name=o.get("name"),
            namespace=o.get("namespace", ""),
            max_restarts=o.get("max_restarts", 0),
            max_task_retries=o.get("max_task_retries", 0),
            max_concurrency=o.get("max_concurrency", 1),
            lifetime=o.get("lifetime", "non_detached"),
            concurrency_groups=o.get("concurrency_groups"),
            class_blob=class_blob,
            class_fn_id=class_fn_id,
            init_args=w._serialize_args(args),
            init_kwargs_blob=serialization.dumps(kwargs) if kwargs else None,
            resources=resources,
            scheduling=scheduling,
            runtime_env=o.get("runtime_env"),
        )
        w.create_actor(spec, class_name=self._cls.__name__)
        return ActorHandle(spec.actor_id, self._cls.__name__)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actors cannot be instantiated directly; use "
            f"`{self._cls.__name__}.remote(...)`.")
